"""Scripted multi-node chaos scenarios over the in-process daemon fabric.

A ChaosScenario records every scripted action (and every wait outcome)
into the shared ChaosEventLog's "scenario" stream, so two runs of the
same timeline from the same seed can be compared with
ChaosEventLog.matches().  Convergence is judged bit-exactly against a
host-oracle recompute of each daemon's routes (oracle_route_dbs) rather
than against another daemon — the oracle cannot itself be perturbed by
the chaos under test.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from ..decision.spf_solver import HostSpfBackend, SpfSolver
from .chaos import (
    SCENARIO_STREAM,
    ChaosEventLog,
    wait_timeout_scale,
    wait_until,
)

FIB_CLIENT = 786


def fib_unicast_routes(daemon) -> dict[str, frozenset]:
    """The daemon's programmed unicast FIB as {dest: next-hop set}."""
    table = daemon.fib_agent.unicast.get(FIB_CLIENT, {})
    return {dest: frozenset(route.next_hops) for dest, route in table.items()}


def oracle_route_dbs(daemon) -> dict[str, frozenset]:
    """Host-oracle recompute of the daemon's own routes.

    Builds a fresh SpfSolver pinned to HostSpfBackend over the daemon's
    current link/prefix state (read inside the decision thread, so no
    torn state) and returns {dest: next-hop set} for installable routes.
    Static routes are not replicated — scenarios compare dynamic state.
    """
    decision = daemon.decision

    def _compute() -> dict[str, frozenset]:
        solver = SpfSolver(
            decision.my_node_name,
            enable_v4=decision.spf_solver.enable_v4,
            bgp_dry_run=decision.spf_solver.bgp_dry_run,
            enable_best_route_selection=(
                decision.spf_solver.enable_best_route_selection
            ),
            spf_backend=HostSpfBackend(),
        )
        db = solver.build_route_db(decision.area_link_states, decision.prefix_state)
        if db is None:
            return {}
        return {
            prefix: frozenset(entry.nexthops)
            for prefix, entry in db.unicast_routes.items()
            if not entry.do_not_install
        }

    return decision.run_in_event_base_thread(_compute).result()


def fib_matches_oracle(daemon) -> bool:
    return fib_unicast_routes(daemon) == oracle_route_dbs(daemon)


def hold_converged(
    daemons, timeout_s: float = 30.0, hold_s: float = 0.5
) -> bool:
    """True once every daemon's FIB bit-exactly matches its own host-
    oracle recompute AND the match holds for a full ``hold_s`` quiescence
    window with no new route publications.

    Two instantaneous polls are not enough on a loaded box: a rebuild can
    land between the FIB read and the oracle read, or (worse) the match
    can be momentarily true while a late update is still queued, so a
    snapshot taken right after the wait races the final write.  The hold
    window requires the match to stay continuously true and pins the
    daemons' route-publication write counters across it — if anything
    publishes mid-window the hold restarts from the new state.
    """

    def _writes() -> tuple[int, ...]:
        return tuple(d.route_updates_queue.get_num_writes() for d in daemons)

    # scale the SEARCH budget for instrumented/overridden runs, never
    # the hold window: quiescence semantics must stay identical (see
    # chaos.wait_timeout_scale's timing model)
    deadline = time.monotonic() + timeout_s * wait_timeout_scale()
    while time.monotonic() < deadline:
        if not all(fib_matches_oracle(d) for d in daemons):
            time.sleep(0.05)
            continue
        w0 = _writes()
        hold_end = time.monotonic() + hold_s
        held = True
        while time.monotonic() < hold_end:
            time.sleep(0.05)
            if _writes() != w0 or not all(
                fib_matches_oracle(d) for d in daemons
            ):
                held = False
                break
        if held and _writes() == w0:
            return True
    return False


class ChaosScenario:
    """A replayable fault timeline: named steps plus logged waits."""

    def __init__(self, log_: Optional[ChaosEventLog] = None) -> None:
        self.log = log_ if log_ is not None else ChaosEventLog()

    def step(self, name: str, fn: Optional[Callable[[], object]] = None):
        """Log a scripted action, then perform it."""
        self.log.append(SCENARIO_STREAM, name)
        return fn() if fn is not None else None

    def wait(
        self,
        name: str,
        cond: Callable[[], bool],
        timeout_s: float = 20.0,
    ) -> bool:
        """Wait on a condition; the outcome is part of the replay log."""
        ok = wait_until(cond, timeout_s)
        self.log.append(SCENARIO_STREAM, f"{name}:{'ok' if ok else 'timeout'}")
        return ok

    def wait_converged(
        self,
        daemons,
        timeout_s: float = 30.0,
        hold_s: float = 0.5,
    ) -> bool:
        """Wait until every daemon's FIB bit-exactly matches its own
        host-oracle recompute AND the match holds for a full ``hold_s``
        quiescence window with no new route publications.

        Two instantaneous polls are not enough on a loaded box: a rebuild
        can land between the FIB read and the oracle read, or (worse) the
        match can be momentarily true while a late update is still queued,
        so a snapshot taken right after the wait races the final write.
        The hold window requires the match to stay true continuously and
        pins the daemons' route-publication write counters across it — if
        anything publishes mid-window the hold restarts from the new state
        (module-level ``hold_converged``).  The log entry stays
        ``converged:ok``/``converged:timeout`` so same-seed replay logs
        still compare equal.
        """
        ok = hold_converged(daemons, timeout_s=timeout_s, hold_s=hold_s)
        self.log.append(
            SCENARIO_STREAM, f"converged:{'ok' if ok else 'timeout'}"
        )
        return ok
