"""Open-loop overload generator for the query-serving layer.

Closed-loop load (wait for each reply before sending the next query)
can never overrun admission: the scheduler's own latency throttles the
clients.  Real overload is open-loop — N independent clients each
submit at their own cadence regardless of whether earlier replies have
arrived — so that is what this generator models.  Each client owns a
`random.Random(seed * 1000 + i)` stream, making the offered load (which
sources, which ops, in which order) a pure function of the seed: two
runs offer bit-identical query sequences, so shed/reply accounting is
comparable across runs.

Two modes:

- `run_burst(per_client)` — every client submits its whole budget as
  fast as the GIL allows, then the generator gathers every future.
  Deterministic enough for tier-1: offered load is exact, and the
  zero-silent-drop invariant (submitted == replied + shed + errors)
  must hold regardless of scheduling.
- `run_paced(duration_s, qps_per_client)` — wall-clock-paced open loop
  for the `-m slow` soak and the bench row: sustained qps with latency
  percentiles.

The report never inspects scheduler internals: it counts what the
*caller* observed (future resolved with a result, a QueryShedError, or
another error), which is exactly the surface the zero-silent-drop
acceptance criterion is stated over.
"""

from __future__ import annotations

import concurrent.futures
import random
import threading
import time
from dataclasses import dataclass, field

from ..serving import QueryShedError


@dataclass
class LoadReport:
    """What the clients observed, summed over all of them."""

    submitted: int = 0
    replied: int = 0
    shed: int = 0
    errors: int = 0
    wall_s: float = 0.0
    latencies_us: list = field(default_factory=list)
    batch_sizes: list = field(default_factory=list)

    @property
    def accounted(self) -> int:
        """Futures that resolved, one way or another.  Zero silent
        drops means accounted == submitted."""
        return self.replied + self.shed + self.errors

    @property
    def qps(self) -> float:
        return self.replied / self.wall_s if self.wall_s > 0 else 0.0

    @property
    def mean_batch_occupancy(self) -> float:
        if not self.batch_sizes:
            return 0.0
        return sum(self.batch_sizes) / len(self.batch_sizes)

    def pctl_us(self, p: int) -> int:
        if not self.latencies_us:
            return 0
        lats = sorted(self.latencies_us)
        return int(lats[min(len(lats) - 1, (len(lats) * p) // 100)])


class OpenLoopLoadGen:
    """Seeded many-client open-loop generator over a QueryScheduler.

    `ops` weights which query kinds each client issues; the default is
    all-paths (single-source queries, the shape the coalescer merges
    into one bucketed program).  `nodes` is the source population.
    """

    def __init__(
        self,
        scheduler,
        nodes: list,
        seed: int = 0,
        clients: int = 8,
        ops: tuple = ("paths",),
        sessions: bool = False,
        on_reply=None,
    ) -> None:
        self.scheduler = scheduler
        self.nodes = list(nodes)
        self.seed = int(seed)
        self.clients = int(clients)
        self.ops = tuple(ops)
        # sessions=True tags every client's queries with a per-client
        # session id for the router's epoch pinning — only valid when
        # `scheduler` accepts a `session` kwarg (serving.ReplicaRouter)
        self.sessions = bool(sessions)
        # on_reply((op, src, session), QueryResult) runs during gather for
        # every successful reply — the chaos families hang their per-epoch
        # bit-exactness oracle checks here
        self.on_reply = on_reply

    def _submit_one(self, rng: random.Random, client_i: int):
        op = rng.choice(self.ops)
        src = rng.choice(self.nodes)
        kw: dict = {}
        session = f"client-{client_i}" if self.sessions else None
        if session is not None:
            kw["session"] = session
        if op == "paths":
            fut = self.scheduler.submit("paths", sources=(src,), **kw)
        elif op == "what_if":
            a, b = rng.sample(self.nodes, 2)
            fut = self.scheduler.submit(
                "what_if", sources=(src,), scenarios=(((a, b),),), **kw
            )
        else:
            dest = rng.choice([n for n in self.nodes if n != src])
            fut = self.scheduler.submit(
                "ksp", sources=(src,), dests=(dest,), **kw
            )
        return fut, (op, src, session)

    def _gather(
        self, futures: list, report: LoadReport, timeout_s: float
    ) -> None:
        deadline = time.monotonic() + timeout_s
        for fut, meta in futures:
            budget = max(0.0, deadline - time.monotonic())
            try:
                res = fut.result(timeout=budget)
            except QueryShedError:
                report.shed += 1
            except concurrent.futures.TimeoutError:
                # an unresolved future IS a silent drop: leave it
                # unaccounted so the invariant check fails loudly
                continue
            except Exception:  # noqa: BLE001
                report.errors += 1
            else:
                report.replied += 1
                report.latencies_us.append(res.latency_us)
                report.batch_sizes.append(res.batch_size)
                if self.on_reply is not None:
                    self.on_reply(meta, res)

    def run_burst(
        self, per_client: int, gather_timeout_s: float = 60.0
    ) -> LoadReport:
        """Every client fires its whole budget open-loop, then the
        report gathers every future."""
        report = LoadReport()
        lock = threading.Lock()
        all_futures: list = []

        def client(i: int) -> None:
            rng = random.Random(self.seed * 1000 + i)
            futures = [self._submit_one(rng, i) for _ in range(per_client)]
            with lock:
                all_futures.extend(futures)

        t0 = time.perf_counter()
        threads = [
            threading.Thread(target=client, args=(i,), name=f"loadgen-{i}")
            for i in range(self.clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        report.submitted = len(all_futures)
        self._gather(all_futures, report, gather_timeout_s)
        report.wall_s = time.perf_counter() - t0
        return report

    def run_paced(
        self,
        duration_s: float,
        qps_per_client: float,
        gather_timeout_s: float = 60.0,
    ) -> LoadReport:
        """Wall-clock-paced open loop: each client submits on its own
        fixed cadence for `duration_s`, never waiting for replies."""
        report = LoadReport()
        lock = threading.Lock()
        all_futures: list = []
        period = 1.0 / qps_per_client if qps_per_client > 0 else 0.0

        def client(i: int) -> None:
            rng = random.Random(self.seed * 1000 + i)
            futures = []
            t_next = time.monotonic()
            t_end = t_next + duration_s
            while time.monotonic() < t_end:
                futures.append(self._submit_one(rng, i))
                t_next += period
                delay = t_next - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
            with lock:
                all_futures.extend(futures)

        t0 = time.perf_counter()
        threads = [
            threading.Thread(target=client, args=(i,), name=f"loadgen-{i}")
            for i in range(self.clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        report.submitted = len(all_futures)
        self._gather(all_futures, report, gather_timeout_s)
        report.wall_s = time.perf_counter() - t0
        return report
