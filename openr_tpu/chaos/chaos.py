"""Deterministic fault injectors: one seed, replayable schedules.

Every injector derives an independent `random.Random` stream per fault
site (directed link, transport edge, agent) from the master seed, so
thread interleaving across sites cannot perturb any one site's decision
sequence: the k-th packet on link A->B sees the same verdict in every
run with the same seed, regardless of what other links are doing.

The `ChaosEventLog` mirrors that structure — one ordered stream per
fault site plus a "scenario" stream for timeline steps — because a
single global ordering would depend on thread scheduling and defeat
replay comparison.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ..spark.io_provider import MockIoProvider

log = logging.getLogger(__name__)

SCENARIO_STREAM = "scenario"


class ChaosEventLog:
    """Per-stream ordered fault record.

    Within a stream the entry order is the decision order — a pure
    function of the seed and the per-site event index.  Across streams
    no order is defined (delivery threads interleave freely), which is
    why `matches` compares stream-by-stream: the scenario stream must
    be identical, fault streams must agree on their common prefix (two
    runs may observe different packet COUNTS — timers drift — but the
    k-th decision at a site is seed-determined)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._streams: dict[str, list[str]] = {}

    def append(self, stream: str, event: str) -> None:
        with self._lock:
            self._streams.setdefault(stream, []).append(event)

    def streams(self) -> dict[str, list[str]]:
        with self._lock:
            return {k: list(v) for k, v in self._streams.items()}

    def scenario(self) -> list[str]:
        with self._lock:
            return list(self._streams.get(SCENARIO_STREAM, []))

    def matches(self, other: "ChaosEventLog") -> bool:
        a, b = self.streams(), other.streams()
        if a.get(SCENARIO_STREAM, []) != b.get(SCENARIO_STREAM, []):
            return False
        for stream in set(a) & set(b):
            ea, eb = a[stream], b[stream]
            n = min(len(ea), len(eb))
            if ea[:n] != eb[:n]:
                return False
        return True


@dataclass
class LinkFaultProfile:
    """Per-directed-link fault rates; all decisions seed-driven."""

    drop: float = 0.0  # P(packet silently dropped)
    dup: float = 0.0  # P(packet delivered twice)
    reorder: float = 0.0  # P(packet delayed past later traffic)
    delay_s: float = 0.0  # fixed extra one-way delay
    jitter_s: float = 0.0  # uniform extra delay in [0, jitter_s)
    reorder_delay_s: float = 0.08  # how far a reordered packet slips


class ChaosIoProvider(MockIoProvider):
    """MockIoProvider with seeded per-link drop/dup/reorder/delay faults
    and node-pair partitions, all replayable from one seed.

    Profiles key on (src node, dst node) — every interface pair between
    the two nodes shares the schedule, which keeps the fault streams
    stable when a test rewires interfaces."""

    def __init__(
        self, seed: int = 0, log_: Optional[ChaosEventLog] = None
    ) -> None:
        super().__init__()
        self.seed = seed
        self.log = log_ or ChaosEventLog()
        self._chaos_lock = threading.Lock()
        self._profiles: dict[tuple[str, str], LinkFaultProfile] = {}
        self._chaos_partitions: set[frozenset[str]] = set()
        self._rngs: dict[tuple[str, str], random.Random] = {}
        self._pkt_index: dict[tuple[str, str], int] = {}

    # -- schedule configuration ---------------------------------------------

    def set_link_profile(
        self,
        node_a: str,
        node_b: str,
        profile: Optional[LinkFaultProfile] = None,
        *,
        symmetric: bool = True,
        **rates,
    ) -> None:
        profile = profile or LinkFaultProfile(**rates)
        with self._chaos_lock:
            self._profiles[(node_a, node_b)] = profile
            if symmetric:
                self._profiles[(node_b, node_a)] = profile

    def clear_link_profile(
        self, node_a: str, node_b: str, *, symmetric: bool = True
    ) -> None:
        with self._chaos_lock:
            self._profiles.pop((node_a, node_b), None)
            if symmetric:
                self._profiles.pop((node_b, node_a), None)

    def clear_all_profiles(self) -> None:
        with self._chaos_lock:
            self._profiles.clear()
            self._chaos_partitions.clear()

    def set_partitioned(
        self, node_a: str, node_b: str, partitioned: bool
    ) -> None:
        """Hard partition: every packet between the two nodes vanishes
        (the spark-fabric analogue of InProcessTransport partitions)."""
        key = frozenset((node_a, node_b))
        with self._chaos_lock:
            if partitioned:
                self._chaos_partitions.add(key)
            else:
                self._chaos_partitions.discard(key)

    # -- fault decisions -----------------------------------------------------

    def _link_rng(self, src_node: str, dst_node: str) -> random.Random:
        key = (src_node, dst_node)
        rng = self._rngs.get(key)
        if rng is None:
            rng = random.Random(f"{self.seed}:{src_node}->{dst_node}")
            self._rngs[key] = rng
        return rng

    def _plan_delivery(self, src_node: str, dst_node: str) -> list[float]:
        """Extra delays for each delivered copy of one packet; [] drops
        it.  One ordered decision stream per directed node pair.

        The packet index (and the RNG) only advances for PROFILED
        packets: partitioned or unprofiled traffic is timing-dependent
        in count, and letting it consume draws would shift every later
        verdict between two same-seed runs.  Keyed this way, the k-th
        profiled packet on a link sees the same fate in every replay."""
        stream = f"link:{src_node}->{dst_node}"
        with self._chaos_lock:
            if frozenset((src_node, dst_node)) in self._chaos_partitions:
                return []
            prof = self._profiles.get((src_node, dst_node))
            if prof is None:
                return [0.0]
            k = self._pkt_index.get((src_node, dst_node), 0)
            self._pkt_index[(src_node, dst_node)] = k + 1
            rng = self._link_rng(src_node, dst_node)
            if prof.drop > 0 and rng.random() < prof.drop:
                self.log.append(stream, f"{k}:drop")
                return []
            delay = prof.delay_s
            if prof.jitter_s > 0:
                delay += rng.random() * prof.jitter_s
            plan = [delay]
            events = []
            if prof.reorder > 0 and rng.random() < prof.reorder:
                plan[0] += prof.reorder_delay_s
                events.append("reorder")
            if prof.dup > 0 and rng.random() < prof.dup:
                plan.append(delay + prof.reorder_delay_s * rng.random())
                events.append("dup")
            if events:
                self.log.append(stream, f"{k}:{'+'.join(events)}")
        return plan

    def _deliver(self, src: tuple[str, str], data: bytes) -> None:
        with self._lock:
            targets = [
                (self._endpoints.get(dst), dst, latency)
                for dst, latency in self._links.get(src, [])
            ]
        for ep, dst, latency in targets:
            if ep is None:
                continue
            for extra in self._plan_delivery(src[0], dst[0]):
                ep._enqueue_after(
                    latency + extra, dst[1], data, f"fe80::{src[0]}"
                )


class FibChaosPlan:
    """Seeded failure schedule for MockFibAgent: per-call program/sync
    errors and spontaneous agent restarts, replayable from the seed.

    The agent consults `on_call(op)` before every thrift-surface call;
    ops draw from ONE stream in call order — deterministic because a
    Fib instance serializes agent calls on its event-base thread."""

    FAIL = "fail"
    RESTART = "restart"
    OK = "ok"

    def __init__(
        self,
        seed: int = 0,
        *,
        fail_prob: float = 0.0,
        restart_prob: float = 0.0,
        fail_ops: Optional[set[str]] = None,
        log_: Optional[ChaosEventLog] = None,
        stream: str = "fib",
    ) -> None:
        self.fail_prob = fail_prob
        self.restart_prob = restart_prob
        self.fail_ops = fail_ops
        self.log = log_ or ChaosEventLog()
        self.stream = stream
        self.armed = True
        self._rng = random.Random(f"{seed}:{stream}")
        self._call_index = 0
        self._lock = threading.Lock()

    def disarm(self) -> None:
        self.armed = False

    def arm(self) -> None:
        self.armed = True

    def on_call(self, op: str) -> str:
        with self._lock:
            if not self.armed:
                return self.OK
            if self.fail_ops is not None and op not in self.fail_ops:
                return self.OK
            k = self._call_index
            self._call_index += 1
            u = self._rng.random()
            if u < self.restart_prob:
                self.log.append(self.stream, f"{k}:{op}:restart")
                return self.RESTART
            if u < self.restart_prob + self.fail_prob:
                self.log.append(self.stream, f"{k}:{op}:fail")
                return self.FAIL
            return self.OK


class KvChaosInjector:
    """Seeded failures on the in-process KvStore transport: flood/full-
    sync request errors per directed store pair, plus stale-TTL storms.

    Wire with `InProcessTransport.set_chaos(injector)`; each bound
    transport call passes (op, src addr, dst addr) and the injector
    raises the transport's error type when the seeded draw says so."""

    def __init__(
        self,
        seed: int = 0,
        *,
        full_dump_fail: float = 0.0,
        key_set_fail: float = 0.0,
        log_: Optional[ChaosEventLog] = None,
    ) -> None:
        self.seed = seed
        self.full_dump_fail = full_dump_fail
        self.key_set_fail = key_set_fail
        self.log = log_ or ChaosEventLog()
        self.armed = True
        self._lock = threading.Lock()
        self._rngs: dict[str, random.Random] = {}
        self._indices: dict[str, int] = {}

    def disarm(self) -> None:
        self.armed = False

    def arm(self) -> None:
        self.armed = True

    def check(self, op: str, src: str, dst: str) -> None:
        """Raises TransportError when the seeded schedule fails this
        call; called by _BoundInProcessTransport before dispatch."""
        prob = {
            "full_dump": self.full_dump_fail,
            "key_set": self.key_set_fail,
        }.get(op, 0.0)
        if prob <= 0:
            return
        stream = f"kv:{op}:{src}->{dst}"
        with self._lock:
            if not self.armed:
                return
            k = self._indices.get(stream, 0)
            self._indices[stream] = k + 1
            rng = self._rngs.get(stream)
            if rng is None:
                rng = random.Random(f"{self.seed}:{stream}")
                self._rngs[stream] = rng
            failed = rng.random() < prob
            if failed:
                self.log.append(stream, f"{k}:fail")
        if failed:
            from ..kvstore.kvstore import TransportError

            raise TransportError(f"injected {op} failure {src}->{dst}")

    def ttl_storm(
        self,
        kvstore,
        area: str = "0",
        n_keys: int = 16,
        ttl_ms: int = 120,
    ) -> list[str]:
        """Stale-TTL storm: flood `n_keys` seeded keys that expire almost
        immediately, exercising the TTL countdown/eviction machinery
        network-wide (every store must age them out consistently)."""
        from ..types import Value

        rng = random.Random(f"{self.seed}:ttl-storm")
        keys = []
        key_vals = {}
        for i in range(n_keys):
            key = f"chaos-ttl-{i}"
            keys.append(key)
            key_vals[key] = Value(
                version=1,
                originator_id="chaos",
                value=rng.randbytes(8),
                ttl_ms=ttl_ms,
            )
        kvstore.set_key_vals(area, key_vals)
        self.log.append("kv:ttl-storm", f"storm:{n_keys}:{ttl_ms}ms")
        return keys


class ChaosSpfBackend:
    """SpfBackend decorator that injects device-dispatch failures on a
    seeded schedule — the handle tests use to prove the Decision
    degradation ladder (device failure -> host oracle, routes intact).

    Forwards the full backend surface (including the optional
    csr_mirror/prefetch attributes the solver probes with getattr) and
    raises before delegating when the schedule says so."""

    def __init__(
        self,
        inner,
        seed: int = 0,
        *,
        fail_prob: float = 0.0,
        fail_ops: Optional[set[str]] = None,
        log_: Optional[ChaosEventLog] = None,
    ) -> None:
        self.inner = inner
        self.fail_prob = fail_prob
        self.fail_ops = fail_ops
        self.log = log_ or ChaosEventLog()
        self.armed = True
        self._rng = random.Random(f"{seed}:spf")
        self._lock = threading.Lock()
        self._call_index = 0
        # device-residency engine seam: faults fire INSIDE the engine's
        # entry points (sync/spf/fleet_product), so an injected failure
        # exercises the same ladder a real device fault would
        engine = getattr(inner, "engine", None)
        if engine is not None:
            engine.fault_hook = lambda op: self._gate(f"engine:{op}")

    def disarm(self) -> None:
        self.armed = False

    def _gate(self, op: str) -> None:
        with self._lock:
            if not self.armed:
                return
            if self.fail_ops is not None and op not in self.fail_ops:
                return
            k = self._call_index
            self._call_index += 1
            if self._rng.random() < self.fail_prob:
                self.log.append("spf", f"{k}:{op}:fail")
                raise RuntimeError(f"injected device dispatch failure: {op}")

    def get_spf_result(self, link_state, src):
        self._gate("get_spf_result")
        return self.inner.get_spf_result(link_state, src)

    def get_kth_paths(self, link_state, src, dest, k):
        self._gate("get_kth_paths")
        return self.inner.get_kth_paths(link_state, src, dest, k)

    def __getattr__(self, name):
        # csr_mirror / prefetch* / min_device_* probe-through, gated the
        # same way so fleet-view construction fails where dispatch would
        attr = getattr(self.inner, name)
        if name in ("csr_mirror", "prefetch", "prefetch_kth_paths"):
            def _wrapped(*args, **kwargs):
                self._gate(name)
                return attr(*args, **kwargs)

            return _wrapped
        return attr


def wait_timeout_scale() -> float:
    """Multiplier applied to every chaos wait/convergence timeout.

    Timing model: chaos timeouts are calibrated on an UNINSTRUMENTED
    1-CPU full-suite run — convergence is a fixed amount of daemon work
    (SPF recomputes, queue drains, FIB programs), so wall time scales
    with per-operation cost, not with the timeout constant.  Arming the
    happens-before race detector (`OPENR_TSAN=1`) multiplies that
    per-operation cost: every queue put/get, lock acquire, eventbase
    handoff, and future resolution takes the detector's vector-clock
    path, and under full-suite load the same scripted timeline can need
    ~2-3x the wall clock to reach the identical converged state.  A
    fixed timeout therefore turns instrumentation overhead into a fake
    liveness failure — the replay-determinism flake — while scaling the
    timeout (never the hold window or the poll cadence: quiescence
    semantics must not change) keeps the pass condition identical and
    only gives the slowed run time to get there.

    `OPENR_CHAOS_TIMEOUT_SCALE` overrides for even slower rigs
    (emulators, heavily shared CI); otherwise 3x whenever the detector
    is armed, 1x unarmed so the calibrated budgets stay tight."""
    env = os.environ.get("OPENR_CHAOS_TIMEOUT_SCALE")
    if env:
        return max(1.0, float(env))
    from ..analysis import race

    if race.TSAN is not None:
        return 3.0
    return 1.0


def wait_until(cond, timeout_s: float = 20.0, poll_s: float = 0.05) -> bool:
    deadline = time.monotonic() + timeout_s * wait_timeout_scale()
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(poll_s)
    return False
