"""Coverage-guided chaos fuzzing: search the failure space, then shrink.

The repo's five seeded chaos families (OCS rewires, delta-rung flap
chunks, KvStore TTL storms, replica-fleet kills/partitions, armed
`engine:*` faults) each script ONE timeline.  This module searches the
*composition space* instead: a corpus of JSON fault timelines is mutated
and crossed over across families, every run is scored by a coverage
fingerprint built from deterministic counter-state deltas and
dispatch-rung traversal (delta / fused-warm / blocked / pipelined /
pallas / rewire / restage), and an oracle bundle is evaluated after
every run.  Timelines that surface new coverage join the corpus;
timelines that violate an oracle are delta-debugged down to a minimal
reproducer and checked in under ``tests/chaos_corpus/`` as auto-collected
regression scenarios.

Determinism contract (what makes a corpus *replayable*):

- every event carries concrete parameters synthesized at mutation time
  — replay never draws from an RNG, so removing an event during
  shrinking cannot shift the interpretation of the events around it;
- events apply *tolerantly*: retiring an absent chord, healing an
  unpartitioned store, or restarting a live replica is a logged no-op,
  so any subsequence of a valid timeline is itself a valid timeline;
- the fingerprint only reads counters whose value is a pure function of
  the timeline (never wall-time `*_us` timers, never cross-run cache
  state like compiles or bucket hits, never load-dependent retry/hedge
  counts), so the same seed reproduces the identical corpus
  (`ChaosEventLog.matches` plus JSON equality, asserted in tier-1).

Oracle bundle (all crash-free failure detectors the repo already has):

- **bit_exact_spf** — engine SPF products vs the host Dijkstra oracle
  on sampled sources, mid-run and at settle;
- **view_exact** — the final fleet view vs a cold engine-less rebuild;
- **ledger_router** — the replica-router dispatch identity closes and
  submitted == replied + shed + errors (zero silent drops);
- **ledger_kv** — every TTL-storm key is accounted by the harness
  ledger and actually expires from every store;
- **restage_bound** — `full_restages` stays within the scripted budget
  (initial uploads + logged rebuilds + accounted rewire demotions);
- **races** — zero unsuppressed findings when `OPENR_TSAN=1` is armed.

CLI: ``python -m openr_tpu.chaos.fuzz --fuzz-n 50 --seed 7 --budget-s
120`` to search, ``--shrink tests/chaos_corpus/entry.json`` to reduce a
failing entry.  ``OPENR_FUZZ_SEED`` seeds the run when --seed is absent.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time
from dataclasses import dataclass, field
from typing import Optional

from .chaos import SCENARIO_STREAM, ChaosEventLog, KvChaosInjector, wait_until
from .scenario import ChaosScenario

# v1 -> v2: the `snapshot` event family (engine snapshot take/restore +
# elastic fleet scale/kill) joined the generator; v1 entries replay
# unchanged semantically but are re-stamped so an old harness can never
# silently drop the new family's events
CORPUS_VERSION = 2
FAMILIES = ("ocs", "flap", "kv", "fleet", "engine", "snapshot")

FUZZ_COUNTER_KEYS = (
    "chaos.fuzz.runs",
    "chaos.fuzz.mutations",
    "chaos.fuzz.crossovers",
    "chaos.fuzz.novel_fingerprints",
    "chaos.fuzz.oracle_failures",
    "chaos.fuzz.shrink_steps",
)

# engine ops the `engine:arm` event may target; each armed fault fires
# exactly once at the next matching engine entry and then disarms, so a
# timeline's fault schedule is position-independent and shrink-safe
ARMABLE_OPS = (
    "sync",
    "spf",
    "rewire",
    "delta_frontier",
    "delta_relax",
    "pallas",
    "blocked_round",
    "blocked_product",
)

# world geometry: a chorded WAN ring (the OCS scenario's shape, scaled
# down for per-run cost) with a fixed far-arc destination cluster
_N = 16
_RING_OFFSETS = (1, -1, 2, -2)
_CHORD_DEG_CAP = 3
_WORSE_METRIC = 70
_DEST_IDS = tuple(range(8, 14))  # 6 labeled destinations, far arc
_FLEET_N = 10  # separate plain ring behind the replica router

# fingerprint whitelist: counters whose per-run delta is a pure function
# of the timeline.  Deliberately EXCLUDED: *_us timers (wall time),
# compiles / bucket_hits / bucket_misses / delta_bucket_* / evictions
# (cross-run cache state on the shared engine), bytes_staged (padding
# detail), and every serving.router retry/hedge count (load-dependent).
_FP_ENGINE_KEYS = (
    "device.engine.full_restages",
    "device.engine.incremental_updates",
    "device.engine.queries",
    "device.engine.rewires",
    "device.engine.rewire_dispatches",
    "device.engine.rewire_fallbacks",
    "device.engine.delta_dispatches",
    "device.engine.delta_overflow_fallbacks",
    "device.engine.epoch_invalidations",
    "device.engine.pallas_products",
    "device.engine.pallas_outer_updates",
    "device.engine.pallas_fallbacks",
    "device.engine.pallas_skips",
)
_FP_BLOCKED_KEYS = (
    "mesh.blocked.products",
    "mesh.blocked.rounds",
    "mesh.blocked.pipeline_fallbacks",
)
_FP_DELTA_KEYS = (
    "decision.delta.updates",
    "decision.delta.noop_updates",
    "decision.delta.fallbacks",
)
# snapshot family: deterministic-per-timeline counters only.  EXCLUDED:
# *_us timers, snapshot.bytes (capacity padding detail), and
# manifest_programs / prewarmed_programs (cross-run program-cache state
# on the shared engine)
_FP_SNAPSHOT_KEYS = (
    "snapshot.taken",
    "snapshot.restores",
    "snapshot.replayed_events",
    "snapshot.replay_fallbacks",
    "snapshot.scaleouts",
    "snapshot.scaleins",
)


class FuzzCounters:
    """Pre-seeded ``chaos.fuzz.*`` registry.  The module-level singleton
    below is wired as the ctrl handler's ``fuzz`` module, so the whole
    family answers one getCounters on both wire surfaces (native ctrl +
    fb303 shim) before any fuzz session ever runs."""

    def __init__(self) -> None:
        self.counters: dict[str, int] = {k: 0 for k in FUZZ_COUNTER_KEYS}

    def get_counters(self) -> dict[str, int]:
        return dict(self.counters)

    def bump(self, name: str, delta: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + delta


FUZZ_COUNTERS = FuzzCounters()


class InjectedFault(RuntimeError):
    """Raised by the one-shot armed fault hook; the harness catches only
    this type (real failures must surface as oracle violations)."""


# -- corpus format -----------------------------------------------------------


@dataclass
class FuzzEvent:
    family: str
    kind: str
    params: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "family": self.family,
            "kind": self.kind,
            "params": dict(self.params),
        }

    @staticmethod
    def from_json(d: dict) -> "FuzzEvent":
        return FuzzEvent(
            family=str(d["family"]),
            kind=str(d["kind"]),
            params=dict(d.get("params", {})),
        )


@dataclass
class FuzzTimeline:
    """One corpus entry: a versioned, self-contained event list.  The
    seed only feeds the per-run KvChaosInjector value stream — event
    application itself never draws randomness."""

    seed: int
    events: list = field(default_factory=list)
    version: int = CORPUS_VERSION
    oracle: str = ""  # set on checked-in reproducers: the violated check
    note: str = ""

    def families(self) -> set:
        return {e.family for e in self.events}

    def to_json(self) -> dict:
        out = {
            "version": self.version,
            "seed": self.seed,
            "events": [e.to_json() for e in self.events],
        }
        if self.oracle:
            out["oracle"] = self.oracle
        if self.note:
            out["note"] = self.note
        return out

    def dumps(self) -> str:
        return json.dumps(self.to_json(), indent=2, sort_keys=True)

    @staticmethod
    def from_json(d: dict) -> "FuzzTimeline":
        version = int(d.get("version", 0))
        if version != CORPUS_VERSION:
            raise ValueError(
                f"corpus version {version} != {CORPUS_VERSION}; "
                "regenerate the entry with the current fuzzer"
            )
        return FuzzTimeline(
            seed=int(d["seed"]),
            events=[FuzzEvent.from_json(e) for e in d.get("events", [])],
            version=version,
            oracle=str(d.get("oracle", "")),
            note=str(d.get("note", "")),
        )

    @staticmethod
    def loads(text: str) -> "FuzzTimeline":
        return FuzzTimeline.from_json(json.loads(text))


# -- the shared engine -------------------------------------------------------

_ENGINE = None


def _shared_engine():
    """One DeviceResidencyEngine for every fuzz run in this process: the
    AOT program cache is per-instance, so sharing amortizes compiles
    across the whole session.  Cross-run cache state (compiles, bucket
    hits, delta-bucket cells) is excluded from the fingerprint for
    exactly this reason."""
    global _ENGINE
    if _ENGINE is None:
        from ..device.engine import DeviceResidencyEngine

        _ENGINE = DeviceResidencyEngine()
    return _ENGINE


# -- per-run world -----------------------------------------------------------


def _name(i: int) -> str:
    return f"z{i % _N:03d}"


def _chord_metric(i: int, j: int) -> int:
    return 3 + (i * 40503 + j * 2654435761) % 7


def _initial_chords() -> set:
    # perfect matching i <-> i + n/2: one chord per node, every ELL row
    # in the K=8 bucket with headroom for chord churn (the OCS layout)
    return {(i, i + _N // 2) for i in range(_N // 2)}


@dataclass
class FuzzRunResult:
    timeline: FuzzTimeline
    log: ChaosEventLog
    ok: bool
    failures: list = field(default_factory=list)  # violated oracle names
    fingerprint: frozenset = frozenset()
    counters: dict = field(default_factory=dict)  # per-run deltas
    applied: int = 0
    skipped: int = 0
    faults_fired: int = 0


class _FuzzWorld:
    """One timeline's blast radius: a chorded-ring LinkState truth, a
    CSR mirror on the shared residency engine, a delta-enabled fleet
    view cache, and lazily-built KvStore / replica-fleet satellites."""

    def __init__(
        self,
        timeline: FuzzTimeline,
        log_: Optional[ChaosEventLog] = None,
        plant: bool = False,
    ) -> None:
        from ..decision.csr import CsrTopology
        from ..decision.fleet import FleetViewCache
        from ..decision.link_state import LinkState
        from .flapstorm import _adj, _base_metric

        self._adj = _adj
        self._base_metric = _base_metric
        self.timeline = timeline
        self.plant = plant
        self.log = log_ if log_ is not None else ChaosEventLog()
        self.scenario = ChaosScenario(self.log)

        self.chords: set = _initial_chords()
        self.flapped: dict[int, int] = {}
        self.down: set = set()
        self.ls = LinkState("0")
        self._push_all()
        self.csr = CsrTopology.from_link_state(self.ls)
        self.engine = _shared_engine()
        self.local: dict[str, int] = {}
        self.cache = FleetViewCache(
            delta=True, bump=self._bump_local, delta_min_p=4
        )
        self.dests = [_name(i) for i in _DEST_IDS]

        # one-shot armed faults: op -> pending fire count
        self.armed: dict[str, int] = {}
        self.fired: list = []
        self.engine.fault_hook = self._fault_hook
        # pin the Pallas policy regardless of OPENR_PALLAS so two runs of
        # the same timeline see the same rung in any environment
        self._saved_pallas = self.engine.pallas_mode
        self.engine.pallas_mode = "off"

        # scripted facts for oracles + fingerprint
        self.rebuilds = 0
        self.rewire_refreshes = 0
        self.delta_registered = 0
        self.view_modes: list = []
        self.spf_mismatches = 0
        self.blocked_failures = 0
        self.tokens: set = set()

        # snapshot satellite: the last taken engine snapshot plus the
        # scripted facts its oracles need (accounted cold demotions feed
        # the restage budget; roundtrip failures are an oracle of their
        # own)
        self.snap = None
        self.snapshot_demotes = 0
        self.snapshot_failures = 0

        # counter baselines (shared engine: everything is diffed)
        self._eng0 = self.engine.get_counters()
        self._blk0 = self.engine.blocked.get_counters()
        from ..snapshot import SNAPSHOT_COUNTERS as _snapc

        self._snapc = _snapc
        self._snap0 = _snapc.get_counters()

        # OPENR_TRACE: drain span-structure tokens accumulated by any
        # EARLIER run so this timeline's fingerprint only sees its own
        from ..obs import trace as _trace

        tr = _trace.TRACE
        if tr is not None:
            tr.drain_structure_tokens()

        # kv satellite (lazy)
        self.kv_fabric = None
        self.kv_stores: list = []
        self.kv_queues: list = []
        self.kv_injector: Optional[KvChaosInjector] = None
        self.kv_keys: set = set()
        self.kv_requested = 0
        self.kv_ledger = 0
        self.kv_partitioned = False

        # fleet satellite (lazy)
        self.fleet = None  # (truth, updates, handles, router, oracle)
        self.fleet_acct = {
            "submitted": 0,
            "replied": 0,
            "shed": 0,
            "errors": 0,
            "mismatches": 0,
            "unknown_epochs": 0,
        }
        self.fleet_seq = 0

    # -- plumbing -------------------------------------------------------------

    def _bump_local(self, name: str, delta: int = 1) -> None:
        self.local[name] = self.local.get(name, 0) + delta

    def _fault_hook(self, op: str) -> None:
        pending = self.armed.get(op, 0)
        if pending > 0:
            self.armed[op] = pending - 1
            self.fired.append(op)
            raise InjectedFault(f"fuzz: injected fault at engine:{op}")

    def _node_db(self, i: int):
        from ..types import AdjacencyDatabase

        me = _name(i)
        adjs = []
        for d in _RING_OFFSETS:
            j = (i + d) % _N
            if d == 1 and i in self.down:
                continue
            metric = self._base_metric(i, j)
            if d == 1 and i in self.flapped:
                metric = self.flapped[i]
            adjs.append(self._adj(me, _name(j), metric))
        for a, b in sorted(self.chords):
            if i == a or i == b:
                j = b if i == a else a
                adjs.append(self._adj(me, _name(j), _chord_metric(a, b)))
        return AdjacencyDatabase(
            this_node_name=me,
            adjacencies=adjs,
            is_overloaded=False,
            node_label=0,
            area="0",
        )

    def _push_all(self) -> None:
        for i in range(_N):
            self.ls.update_adjacency_database(self._node_db(i))

    def _refresh(self) -> None:
        """Push the current truth into the CSR mirror; a rebuild (new
        ELL object) is a scripted fact the restage-bound oracle budgets
        for, a rewire stays on the masked-write rung."""
        ell_before = self.csr.ell
        rewired = self.csr.refresh(self.ls)
        if self.csr.ell is not ell_before:
            self.rebuilds += 1
            self.scenario.step("fuzz:refresh:rebuild")
            self.tokens.add("refresh:rebuild")
        elif rewired:
            self.rewire_refreshes += 1
            self.scenario.step("fuzz:refresh:rewire")
            self.tokens.add("refresh:rewire")

    def _chord_ok(self, pair: tuple) -> bool:
        if len(pair) != 2:
            return False
        a, b = int(pair[0]) % _N, int(pair[1]) % _N
        if a == b:
            return False
        a, b = min(a, b), max(a, b)
        if (a, b) in self.chords:
            return False
        if (b - a) in (1, 2) or _N - (b - a) in (1, 2):
            return False  # ring edge
        deg: dict[int, int] = {}
        for x, y in self.chords:
            deg[x] = deg.get(x, 0) + 1
            deg[y] = deg.get(y, 0) + 1
        return (
            deg.get(a, 0) < _CHORD_DEG_CAP and deg.get(b, 0) < _CHORD_DEG_CAP
        )

    def _retry_injected(self, fn):
        """Run `fn`; when a one-shot armed fault escapes to here, log it
        and retry once (the fault is disarmed by firing).  Only our own
        InjectedFault is caught — real failures propagate into the run's
        failure list."""
        try:
            return fn()
        except InjectedFault as exc:
            self.scenario.step(f"fuzz:fault:fired:{exc}")
            return fn()

    def _view(self):
        self._refresh()  # one shared CSR mirror for every rung in the run
        view = self._retry_injected(
            lambda: self.cache.view(
                self.ls, self.dests, csr=self.csr, engine=self.engine
            )
        )
        if (
            view is not None
            and not self.delta_registered
            and view._dist_dev is not None
        ):
            # account the one full product upload a delta chain rides on
            self.engine.delta_register(
                view._dist_dev.nbytes + view._bitmap_dev.nbytes
            )
            self.delta_registered = 1
        if view is not None:
            self.view_modes.append(view.warm_mode)
            self.tokens.add(f"mode:{view.warm_mode}")
            if view.cold_fallback:
                self.tokens.add("mode:cold_fallback")
        return view

    def _spf_exact(self, offset: int) -> bool:
        self._refresh()
        names = self.ls.node_names
        sources = [names[(offset + 5 * k) % len(names)] for k in range(3)]

        def _q():
            return self.engine.spf_results(self.csr, sources)

        got = self._retry_injected(_q)
        for s in sources:
            oracle = self.ls.run_spf(s)
            res = got[s]
            if {k: v.metric for k, v in oracle.items()} != {
                k: v.metric for k, v in res.items()
            }:
                return False
            for node in oracle:
                if oracle[node].next_hops != res[node].next_hops:
                    return False
        return True

    # -- event appliers: ocs --------------------------------------------------

    def _ev_ocs_swap(self, p: dict) -> None:
        victim = tuple(int(x) for x in p.get("victim", ()))
        fresh = tuple(int(x) for x in p.get("fresh", ()))
        did = []
        if len(victim) == 2:
            victim = (min(victim) % _N, max(victim) % _N)
            if victim in self.chords:
                self.chords.discard(victim)
                did.append("retire")
        if len(fresh) == 2 and self._chord_ok(fresh):
            a, b = int(fresh[0]) % _N, int(fresh[1]) % _N
            self.chords.add((min(a, b), max(a, b)))
            did.append("program")
        self.scenario.step(
            f"fuzz:ocs:swap:{victim}->{fresh}:{'+'.join(did) or 'noop'}"
        )
        if did:
            self._push_all()
            self._refresh()
            self.tokens.add("ocs:swap")

    # -- event appliers: flap -------------------------------------------------

    def _flap(self, kind: str, node: int) -> None:
        node = int(node) % _N
        if kind == "worsen":
            self.flapped[node] = _WORSE_METRIC
        elif kind == "restore":
            self.flapped.pop(node, None)
        elif kind == "down":
            self.down.add(node)
        else:  # up
            self.down.discard(node)
        self.ls.update_adjacency_database(self._node_db(node))
        self.scenario.step(f"fuzz:flap:{node}:{kind}")
        self.tokens.add(f"flap:{kind}")

    def _ev_flap_worsen(self, p: dict) -> None:
        self._flap("worsen", p.get("node", 0))

    def _ev_flap_restore(self, p: dict) -> None:
        self._flap("restore", p.get("node", 0))

    def _ev_flap_down(self, p: dict) -> None:
        self._flap("down", p.get("node", 0))

    def _ev_flap_up(self, p: dict) -> None:
        self._flap("up", p.get("node", 0))

    def _ev_flap_chunk(self, p: dict) -> None:
        # the pending flap batch coalesces into ONE rebuild through the
        # cache — the delta rung when eligible, warm/cold otherwise
        view = self._view()
        mode = view.warm_mode if view is not None else None
        self.scenario.step(f"fuzz:flap:chunk:{mode}")

    # -- event appliers: kv ---------------------------------------------------

    def _ensure_kv(self) -> None:
        if self.kv_fabric is not None:
            return
        from ..kvstore import InProcessTransport, KvStore
        from ..runtime.queue import ReplicateQueue
        from ..types import PeerSpec

        self.kv_fabric = InProcessTransport()
        self.kv_injector = KvChaosInjector(
            seed=self.timeline.seed, log_=self.log
        )
        self.kv_fabric.set_chaos(self.kv_injector)
        for nm in ("fz-a", "fz-b"):
            updates: ReplicateQueue = ReplicateQueue()
            syncs: ReplicateQueue = ReplicateQueue()
            peerq: ReplicateQueue = ReplicateQueue()
            store = KvStore(
                nm,
                updates,
                syncs,
                peerq.get_reader(),
                transport=self.kv_fabric.bind(nm),
                areas=("0",),
            )
            self.kv_fabric.register(nm, store)
            store.run()
            self.kv_stores.append(store)
            self.kv_queues.append((updates, syncs, peerq))
        self.kv_stores[0].add_peers("0", {"fz-b": PeerSpec(peer_addr="fz-b")})
        self.kv_stores[1].add_peers("0", {"fz-a": PeerSpec(peer_addr="fz-a")})
        self.scenario.step("fuzz:kv:up")

    def _ev_kv_ttl_storm(self, p: dict) -> None:
        self._ensure_kv()
        n_keys = max(1, min(int(p.get("n_keys", 8)), 64))
        ttl_ms = max(60, min(int(p.get("ttl_ms", 150)), 1000))
        origin = int(p.get("origin", 0)) % len(self.kv_stores)
        keys = self.kv_injector.ttl_storm(
            self.kv_stores[origin], n_keys=n_keys, ttl_ms=ttl_ms
        )
        self.kv_requested += n_keys
        # harness expiry ledger: every planted key must be accounted.
        # `plant` is the shrinker's seeded bug — it drops one key from
        # the ledger per storm, so ledger_kv fails deterministically.
        self.kv_ledger += len(keys) - 1 if self.plant else len(keys)
        self.kv_keys.update(keys)
        self.scenario.step(f"fuzz:kv:ttl_storm:{origin}:{n_keys}:{ttl_ms}")
        self.tokens.add("kv:storm")

    def _ev_kv_partition(self, p: dict) -> None:
        self._ensure_kv()
        self.kv_fabric.set_partitioned("fz-a", "fz-b", True)
        self.kv_partitioned = True
        self.scenario.step("fuzz:kv:partition")
        self.tokens.add("kv:partition")

    def _ev_kv_heal(self, p: dict) -> None:
        if self.kv_fabric is None or not self.kv_partitioned:
            self.scenario.step("fuzz:kv:heal:noop")
            return
        self.kv_fabric.set_partitioned("fz-a", "fz-b", False)
        self.kv_partitioned = False
        self.scenario.step("fuzz:kv:heal")

    # -- event appliers: fleet ------------------------------------------------

    def _fleet_name(self, i: int) -> str:
        return f"q{i % _FLEET_N:03d}"

    def _fleet_db(self, i: int, flapped: dict):
        from ..types import AdjacencyDatabase

        me = self._fleet_name(i)
        adjs = []
        for d in _RING_OFFSETS:
            j = (i + d) % _FLEET_N
            metric = self._base_metric(i, j)
            if d == 1 and i in flapped:
                metric = flapped[i]
            adjs.append(self._adj(me, self._fleet_name(j), metric))
        return AdjacencyDatabase(
            this_node_name=me,
            adjacencies=adjs,
            is_overloaded=False,
            node_label=0,
            area="0",
        )

    def _ensure_fleet(self) -> None:
        if self.fleet is not None:
            return
        from ..decision.link_state import LinkState
        from ..decision.spf_solver import DeviceSpfBackend
        from ..serving import (
            EngineBatchBackend,
            QueryScheduler,
            ReplicaRouter,
        )
        from .replicafleet import ChaosReplicaHandle

        def build_ls() -> "LinkState":
            ls = LinkState("0")
            for i in range(_FLEET_N):
                ls.update_adjacency_database(self._fleet_db(i, {}))
            return ls

        truth = build_ls()
        handles = []
        for i in range(2):
            ls = build_ls()
            # ride the shared engine: replica SPF dispatches reuse the
            # session-wide program cache instead of recompiling per run
            backend = EngineBatchBackend(
                {"0": ls}, spf_backend=DeviceSpfBackend(engine=self.engine)
            )
            sched = QueryScheduler(backend)
            sched.run()
            handles.append(ChaosReplicaHandle(f"fz-replica-{i}", sched, ls))
        # hedging off: hedge counts are wall-time dependent and would
        # make reply routing (not correctness) vary run to run
        router = ReplicaRouter(handles, hedge_after_s=None)
        oracle: dict[int, dict] = {}
        self.fleet = {
            "truth": truth,
            "updates": [],
            "flapped": {},
            "handles": handles,
            "router": router,
            "oracle": oracle,
        }
        self._fleet_oracle()
        self.scenario.step("fuzz:fleet:up:replicas=2")

    def _fleet_oracle(self) -> None:
        f = self.fleet
        truth = f["truth"]
        epoch = int(truth.version)
        if epoch in f["oracle"]:
            return
        snap = {}
        for src in truth.node_names:
            res = truth.run_spf(src)
            snap[src] = {
                dest: (entry.metric, frozenset(entry.next_hops))
                for dest, entry in res.items()
            }
        f["oracle"][epoch] = snap

    def _fleet_catch_up(self, handle) -> None:
        f = self.fleet
        for db in f["updates"][handle.applied :]:
            handle.ls.update_adjacency_database(db)
        handle.applied = len(f["updates"])

    def _ev_fleet_kill(self, p: dict) -> None:
        self._ensure_fleet()
        h = self.fleet["handles"][int(p.get("idx", 0)) % 2]
        if h.killed:
            self.scenario.step(f"fuzz:fleet:kill:{h.name}:noop")
            return
        h.killed = True
        h.scheduler.stop()
        self.scenario.step(f"fuzz:fleet:kill:{h.name}")
        self.tokens.add("fleet:kill")

    def _ev_fleet_restart(self, p: dict) -> None:
        self._ensure_fleet()
        from ..serving import QueryScheduler

        h = self.fleet["handles"][int(p.get("idx", 0)) % 2]
        if not h.killed:
            self.scenario.step(f"fuzz:fleet:restart:{h.name}:noop")
            return
        h.scheduler = QueryScheduler(h.scheduler.backend)
        h.scheduler.run()
        self._fleet_catch_up(h)
        h.killed = False
        self.fleet["router"].probe_replicas()
        self.scenario.step(f"fuzz:fleet:restart:{h.name}")
        self.tokens.add("fleet:restart")

    def _ev_fleet_partition(self, p: dict) -> None:
        self._ensure_fleet()
        h = self.fleet["handles"][int(p.get("idx", 0)) % 2]
        if h.partitioned:
            self.scenario.step(f"fuzz:fleet:partition:{h.name}:noop")
            return
        h.partitioned = True
        self.scenario.step(f"fuzz:fleet:partition:{h.name}")
        self.tokens.add("fleet:partition")

    def _ev_fleet_heal(self, p: dict) -> None:
        self._ensure_fleet()
        h = self.fleet["handles"][int(p.get("idx", 0)) % 2]
        if not h.partitioned:
            self.scenario.step(f"fuzz:fleet:heal:{h.name}:noop")
            return
        h.partitioned = False
        self._fleet_catch_up(h)
        self.fleet["router"].probe_replicas()
        self.scenario.step(f"fuzz:fleet:heal:{h.name}")

    def _ev_fleet_flap(self, p: dict) -> None:
        self._ensure_fleet()
        f = self.fleet
        node = int(p.get("node", 0)) % _FLEET_N
        if node in f["flapped"]:
            del f["flapped"][node]
            kind = "restore"
        else:
            f["flapped"][node] = _WORSE_METRIC
            kind = "worsen"
        db = self._fleet_db(node, f["flapped"])
        f["truth"].update_adjacency_database(db)
        f["updates"].append(db)
        self._fleet_oracle()
        for h in f["handles"]:
            if not h.killed and not h.partitioned:
                self._fleet_catch_up(h)
        self.scenario.step(f"fuzz:fleet:flap:{node}:{kind}")
        self.tokens.add("fleet:flap")

    def _ev_fleet_burst(self, p: dict) -> None:
        self._ensure_fleet()
        import concurrent.futures

        from ..serving import QueryShedError

        f = self.fleet
        acct = self.fleet_acct
        q = max(1, min(int(p.get("q", 4)), 16))
        self.scenario.step(f"fuzz:fleet:burst:{q}")
        names = f["truth"].node_names
        for k in range(q):
            src = names[(self.fleet_seq + k) % len(names)]
            acct["submitted"] += 1
            fut = f["router"].submit("paths", sources=(src,))
            try:
                res = fut.result(timeout=30)
            except QueryShedError:
                acct["shed"] += 1
                continue
            except concurrent.futures.TimeoutError:
                # an unresolved future IS a silent drop: leave it
                # unaccounted so accounted == submitted fails loudly
                continue
            except Exception:  # noqa: BLE001
                acct["errors"] += 1
                continue
            acct["replied"] += 1
            snap = f["oracle"].get(int(res.epoch))
            if snap is None:
                acct["unknown_epochs"] += 1
                continue
            got = res.value.get(src)
            want = snap.get(src, {})
            got_view = (
                {}
                if got is None
                else {
                    dest: (entry.metric, frozenset(entry.next_hops))
                    for dest, entry in got.items()
                }
            )
            if got_view != want:
                acct["mismatches"] += 1
        self.fleet_seq += q
        self.tokens.add("fleet:burst")

    # -- event appliers: engine -----------------------------------------------

    def _ev_engine_arm(self, p: dict) -> None:
        op = str(p.get("op", "spf"))
        if op not in ARMABLE_OPS:
            self.scenario.step(f"fuzz:engine:arm:{op}:skip")
            return
        self.armed[op] = self.armed.get(op, 0) + 1
        self.scenario.step(f"fuzz:engine:arm:{op}")
        self.tokens.add(f"arm:{op}")

    def _ev_engine_pallas_mode(self, p: dict) -> None:
        mode = str(p.get("mode", "interpret"))
        if mode not in ("off", "interpret"):
            mode = "off"
        self.engine.pallas_mode = mode
        self.scenario.step(f"fuzz:engine:pallas_mode:{mode}")
        self.tokens.add(f"pallas_mode:{mode}")

    def _ev_engine_spf(self, p: dict) -> None:
        exact = self._spf_exact(int(p.get("off", 0)))
        if not exact:
            self.spf_mismatches += 1
        self.scenario.step(
            f"fuzz:engine:spf:{'exact' if exact else 'DIVERGED'}"
        )
        self.tokens.add("engine:spf")

    def _ev_engine_blocked(self, p: dict) -> None:
        import numpy as np

        from ..ops import allsources as asrc

        self._refresh()
        out = asrc.build_out_ell(
            self.csr.edge_src,
            self.csr.edge_dst,
            int(self.csr.n_edges),
            int(self.csr.n_nodes),
            out_slot=getattr(self.csr, "out_slot", None),
        )
        dest_ids = np.arange(int(self.csr.n_nodes), dtype=np.int32)

        def _run():
            return self.engine.blocked.fleet_product(
                self.csr, dest_ids, out
            )

        _dist, _bitmap, ok = self._retry_injected(_run)
        if not ok:
            self.blocked_failures += 1
        self.scenario.step(
            f"fuzz:engine:blocked:{'ok' if ok else 'FAILED'}"
        )
        self.tokens.add("engine:blocked")

    # -- event appliers: snapshot ---------------------------------------------
    #
    # Engine snapshots over the world's own (engine, csr) pair plus
    # elastic membership on the fleet satellite.  Scripted step labels
    # carry only timeline-deterministic facts: the restore rung is one
    # (same world state -> same rung), but blob length and manifest size
    # depend on cross-run program-cache state and stay out of the log.

    def _ev_snapshot_take(self, p: dict) -> None:
        from ..snapshot import EngineSnapshot

        snap = self._retry_injected(
            lambda: EngineSnapshot.take(self.engine, self.csr)
        )
        blob = snap.to_bytes()
        # the wire format must roundtrip byte-identically through its
        # digest check; a planted corruption is caught by from_bytes
        try:
            if EngineSnapshot.from_bytes(blob).to_bytes() != blob:
                self.snapshot_failures += 1
        except Exception:  # noqa: BLE001 — any raise is the violation
            self.snapshot_failures += 1
        self.snap = snap
        self.scenario.step("fuzz:snapshot:take")
        self.tokens.add("snapshot:take")

    def _ev_snapshot_restore(self, p: dict) -> None:
        if self.snap is None:
            self.scenario.step("fuzz:snapshot:restore:noop")
            return
        eng0 = self.engine.get_counters()
        mode = self._retry_injected(
            lambda: self.snap.restore(self.engine, self.csr)
        )
        eng1 = self.engine.get_counters()
        # a cold demotion restages once; a rewire fallback inside the
        # replay sync is already budgeted by the rewire_falls term
        d_restage = (
            eng1["device.engine.full_restages"]
            - eng0["device.engine.full_restages"]
        )
        d_falls = (
            eng1["device.engine.rewire_fallbacks"]
            - eng0["device.engine.rewire_fallbacks"]
        )
        self.snapshot_demotes += max(0, d_restage - d_falls)
        self.scenario.step(f"fuzz:snapshot:restore:{mode}")
        self.tokens.add(f"snapshot:restore:{mode}")

    def _ev_snapshot_scale(self, p: dict) -> None:
        self._ensure_fleet()
        from ..decision.spf_solver import DeviceSpfBackend
        from ..serving import EngineBatchBackend, QueryScheduler
        from ..snapshot import EngineSnapshot
        from .replicafleet import ChaosReplicaHandle

        f = self.fleet
        handles = f["handles"]
        # bound the satellite: at most two joiners per run (a fuzzer
        # that minted a replica per event would own the wall clock)
        if len(handles) >= 4:
            self.scenario.step("fuzz:snapshot:scale:noop")
            return
        i = len(handles)
        from ..decision.link_state import LinkState

        ls = LinkState("0")
        for node in range(_FLEET_N):
            ls.update_adjacency_database(self._fleet_db(node, {}))
        backend = EngineBatchBackend(
            {"0": ls}, spf_backend=DeviceSpfBackend(engine=self.engine)
        )
        sched = QueryScheduler(backend)
        sched.run()
        handle = ChaosReplicaHandle(f"fz-replica-{i}", sched, ls)
        self._fleet_catch_up(handle)
        donor = handles[0]
        mode = "skipped"
        try:
            d_spf = donor.scheduler.backend.spf
            snap = self._retry_injected(
                lambda: EngineSnapshot.take(
                    self.engine, d_spf.csr_mirror(donor.ls)
                )
            )
            eng0 = self.engine.get_counters()
            mode = self._retry_injected(
                lambda: snap.restore(
                    self.engine, backend.spf.csr_mirror(ls)
                )
            )
            eng1 = self.engine.get_counters()
            d_restage = (
                eng1["device.engine.full_restages"]
                - eng0["device.engine.full_restages"]
            )
            d_falls = (
                eng1["device.engine.rewire_fallbacks"]
                - eng0["device.engine.rewire_fallbacks"]
            )
            self.snapshot_demotes += max(0, d_restage - d_falls)
        except Exception:  # noqa: BLE001 — warm start is best-effort
            mode = "skipped"
        handles.append(handle)
        f["router"].add_replica(handle)
        self._snapc._bump("snapshot.scaleouts")
        self.scenario.step(f"fuzz:snapshot:scale:{handle.name}:{mode}")
        self.tokens.add("snapshot:scale")

    def _ev_snapshot_kill(self, p: dict) -> None:
        f = self.fleet
        joined = (
            []
            if f is None
            else [
                h
                for h in f["handles"]
                if not h.killed and h.name >= "fz-replica-2"
            ]
        )
        if not joined:
            self.scenario.step("fuzz:snapshot:kill:noop")
            return
        handle = joined[-1]
        # leave the handle in the list (killed): the restage budget
        # counts replicas ever minted, and settle skips dead schedulers
        f["router"].remove_replica(handle.name)
        handle.killed = True
        handle.scheduler.stop()
        self._snapc._bump("snapshot.scaleins")
        self.scenario.step(f"fuzz:snapshot:kill:{handle.name}")
        self.tokens.add("snapshot:kill")

    # -- run ------------------------------------------------------------------

    def apply(self, ev: FuzzEvent) -> bool:
        fn = getattr(self, f"_ev_{ev.family}_{ev.kind}", None)
        if fn is None:
            self.scenario.step(f"fuzz:skip:{ev.family}:{ev.kind}")
            return False
        self.tokens.add(f"family:{ev.family}")
        fn(ev.params)
        return True

    def settle_and_check(self) -> list:
        """Heal, quiesce, and evaluate the oracle bundle.  Returns the
        violated oracle names (empty == the run is clean)."""
        failures = []
        sc = self.scenario

        # final SPF sweep: engine vs host Dijkstra on sampled sources
        sc.step("fuzz:settle")
        if not self._spf_exact(0) or self.spf_mismatches:
            failures.append("bit_exact_spf")

        # final view vs a cold engine-less rebuild of the same snapshot
        if self.view_modes:
            import numpy as np

            from ..decision.fleet import FleetViewCache

            view = self._view()
            cold = FleetViewCache().view(self.ls, self.dests)
            exact = (
                view is not None
                and cold is not None
                and np.array_equal(
                    np.asarray(view._dist_dev), np.asarray(cold._dist_dev)
                )
                and np.array_equal(
                    np.asarray(view._bitmap_dev),
                    np.asarray(cold._bitmap_dev),
                )
            )
            if not exact:
                failures.append("view_exact")

        if self.blocked_failures:
            failures.append("blocked_ok")

        # snapshot: the wire format must have roundtripped through its
        # digest check every time a take event fired
        if self.snapshot_failures:
            failures.append("snapshot_roundtrip")

        # kv: heal, then every storm key must expire from every store
        # and the harness ledger must account every planted key
        if self.kv_fabric is not None:
            if self.kv_partitioned:
                self._ev_kv_heal({})
            if self.kv_keys:
                keys = sorted(self.kv_keys)

                def _expired() -> bool:
                    for store in self.kv_stores:
                        kvs = store.get_key_vals("0", keys).key_vals
                        if kvs:
                            return False
                    return True

                if not wait_until(_expired, timeout_s=10.0):
                    failures.append("ledger_kv")
                elif self.kv_ledger != self.kv_requested:
                    failures.append("ledger_kv")
            sc.step("fuzz:kv:settled")

        # fleet: stop BEFORE reading the ledger (scheduler stop joins
        # the executors, so every router callback has finished), then
        # the dispatch identity must close with zero silent drops
        if self.fleet is not None:
            from ..serving.router import dispatch_ledger_closes

            f = self.fleet
            f["router"].stop()
            for h in f["handles"]:
                if not h.killed:
                    h.scheduler.stop()
            acct = self.fleet_acct
            counters = f["router"].get_counters()
            accounted = acct["replied"] + acct["shed"] + acct["errors"]
            if accounted != acct["submitted"]:
                failures.append("silent_drops")
            if not dispatch_ledger_closes(counters, acct["submitted"]):
                failures.append("ledger_router")
            if acct["mismatches"] or acct["unknown_epochs"]:
                failures.append("bit_exact_fleet")
            sc.step("fuzz:fleet:settled")

        # restage bound: the initial csr upload + the delta baseline +
        # every logged rebuild + every accounted rewire demotion — and
        # nothing else.  Runaway restaging is the regression this guards.
        eng = self.engine.get_counters()
        restages = (
            eng["device.engine.full_restages"]
            - self._eng0["device.engine.full_restages"]
        )
        rewire_falls = (
            eng["device.engine.rewire_fallbacks"]
            - self._eng0["device.engine.rewire_fallbacks"]
        )
        budget = (
            1
            + self.delta_registered
            + self.rebuilds
            + rewire_falls
            # every accounted snapshot demotion is a scripted cold build
            + self.snapshot_demotes
        )
        # the cache's internal CSR mirror restages independently of the
        # engine-query mirror: one more allowed first contact per run
        if self.view_modes:
            budget += 1 + self.rebuilds
        # each fleet replica's LinkState mirror is fresh per run: first
        # query through it uploads once (attribute flaps after that are
        # incremental)
        if self.fleet is not None:
            budget += len(self.fleet["handles"])
        if restages > budget:
            failures.append("restage_bound")

        # races: zero unsuppressed findings when OPENR_TSAN is armed
        from ..analysis import race

        if race.TSAN is not None:
            findings = race.TSAN.drain()
            if findings:
                failures.append("races")
                sc.step(f"fuzz:races:{len(findings)}")

        sc.step(
            f"fuzz:settled:{'clean' if not failures else ','.join(failures)}"
        )
        return failures

    def fingerprint(self) -> frozenset:
        """Coverage tokens: log2-bucketed deltas of the deterministic
        counter whitelist plus the scripted rung/fault facts collected
        while the timeline ran."""
        tokens = set(self.tokens)
        eng = self.engine.get_counters()
        blk = self.engine.blocked.get_counters()
        for key in _FP_ENGINE_KEYS:
            d = eng.get(key, 0) - self._eng0.get(key, 0)
            if d > 0:
                tokens.add(f"{key}:{d.bit_length()}")
        for key in _FP_BLOCKED_KEYS:
            d = blk.get(key, 0) - self._blk0.get(key, 0)
            if d > 0:
                tokens.add(f"{key}:{d.bit_length()}")
        for key in _FP_DELTA_KEYS:
            d = self.local.get(key, 0)
            if d > 0:
                tokens.add(f"{key}:{d.bit_length()}")
        snapc = self._snapc.get_counters()
        for key in _FP_SNAPSHOT_KEYS:
            d = snapc.get(key, 0) - self._snap0.get(key, 0)
            if d > 0:
                tokens.add(f"{key}:{d.bit_length()}")
        for op in self.fired:
            tokens.add(f"fault:{op}")
        # span-tree structure as a novelty signal: a new retry/hedge edge
        # or rung attribution shape counts as coverage even when every
        # counter bucket is already known (determinism contract makes
        # these byte-stable across same-seed replays)
        from ..obs import trace as _trace

        tr = _trace.TRACE
        if tr is not None:
            for t in tr.drain_structure_tokens():
                tokens.add("span:" + t)
        return frozenset(tokens)

    def counter_deltas(self) -> dict:
        eng = self.engine.get_counters()
        out = {
            k: eng.get(k, 0) - self._eng0.get(k, 0) for k in _FP_ENGINE_KEYS
        }
        blk = self.engine.blocked.get_counters()
        out.update(
            {k: blk.get(k, 0) - self._blk0.get(k, 0) for k in _FP_BLOCKED_KEYS}
        )
        out.update({k: self.local.get(k, 0) for k in _FP_DELTA_KEYS})
        snapc = self._snapc.get_counters()
        out.update(
            {
                k: snapc.get(k, 0) - self._snap0.get(k, 0)
                for k in _FP_SNAPSHOT_KEYS
            }
        )
        return out

    def close(self) -> None:
        self.engine.fault_hook = None
        self.engine.pallas_mode = self._saved_pallas
        # release the run's device residency: csr mirrors are per-run
        # objects, keeping them resident would leak across the session
        self.engine.drop(self.csr)
        if self.fleet is not None:
            f = self.fleet
            try:
                f["router"].stop()
            except Exception:  # noqa: BLE001 — already stopped at settle
                pass
            for h in f["handles"]:
                try:
                    if not h.killed:
                        h.scheduler.stop()
                except Exception:  # noqa: BLE001
                    pass
        for store in self.kv_stores:
            store.stop()
        for updates, syncs, peerq in self.kv_queues:
            updates.close()
            syncs.close()
            peerq.close()
        for store in self.kv_stores:
            store.wait_until_stopped(5)


def run_timeline(
    timeline: FuzzTimeline,
    log_: Optional[ChaosEventLog] = None,
    plant: bool = False,
) -> FuzzRunResult:
    """Replay one corpus entry against a fresh world; deterministic for
    a fixed (timeline, plant) pair — asserted by the tier-1 smoke."""
    world = _FuzzWorld(timeline, log_=log_, plant=plant)
    applied = skipped = 0
    try:
        world.scenario.step(
            f"fuzz:run:v{timeline.version}:seed={timeline.seed}"
            f":events={len(timeline.events)}"
        )
        for ev in timeline.events:
            if world.apply(ev):
                applied += 1
            else:
                skipped += 1
        failures = world.settle_and_check()
        fingerprint = world.fingerprint()
        counters = world.counter_deltas()
    finally:
        world.close()
    FUZZ_COUNTERS.bump("chaos.fuzz.runs")
    return FuzzRunResult(
        timeline=timeline,
        log=world.log,
        ok=not failures,
        failures=failures,
        fingerprint=fingerprint,
        counters=counters,
        applied=applied,
        skipped=skipped,
        faults_fired=len(world.fired),
    )


# -- generation: seeds, mutation, crossover ----------------------------------


def _rand_event(rng: random.Random, family: str) -> FuzzEvent:
    """One concrete event; all parameters are synthesized HERE so replay
    and shrinking never consult an RNG."""
    if family == "ocs":
        a = rng.randrange(_N)
        return FuzzEvent(
            "ocs",
            "swap",
            {
                "victim": [a, (a + _N // 2) % _N],
                "fresh": sorted(
                    (rng.randrange(_N), (rng.randrange(3, _N - 3)))
                ),
            },
        )
    if family == "flap":
        kind = rng.choice(("worsen", "restore", "down", "up", "chunk"))
        if kind == "chunk":
            return FuzzEvent("flap", "chunk", {})
        return FuzzEvent("flap", kind, {"node": rng.randrange(_N)})
    if family == "kv":
        kind = rng.choice(("ttl_storm", "ttl_storm", "partition", "heal"))
        if kind == "ttl_storm":
            return FuzzEvent(
                "kv",
                "ttl_storm",
                {
                    "n_keys": rng.randrange(4, 25),
                    "ttl_ms": rng.randrange(80, 260),
                    "origin": rng.randrange(2),
                },
            )
        return FuzzEvent("kv", kind, {})
    if family == "fleet":
        kind = rng.choice(
            ("burst", "burst", "kill", "restart", "partition", "heal", "flap")
        )
        if kind == "burst":
            return FuzzEvent("fleet", "burst", {"q": rng.randrange(2, 7)})
        if kind == "flap":
            return FuzzEvent(
                "fleet", "flap", {"node": rng.randrange(_FLEET_N)}
            )
        return FuzzEvent("fleet", kind, {"idx": rng.randrange(2)})
    if family == "snapshot":
        # take/restore on the world mirror; scale/kill on the fleet
        # satellite.  All kinds are tolerant no-ops when their target
        # state is absent (restore before take, kill before scale), so
        # shrinking can delete any prefix
        kind = rng.choice(
            ("take", "restore", "restore", "scale", "kill")
        )
        return FuzzEvent("snapshot", kind, {})
    # engine
    kind = rng.choice(("arm", "spf", "spf", "pallas_mode", "blocked"))
    if kind == "arm":
        return FuzzEvent("engine", "arm", {"op": rng.choice(ARMABLE_OPS)})
    if kind == "pallas_mode":
        return FuzzEvent(
            "engine",
            "pallas_mode",
            {"mode": rng.choice(("interpret", "off"))},
        )
    if kind == "blocked":
        return FuzzEvent("engine", "blocked", {})
    return FuzzEvent("engine", "spf", {"off": rng.randrange(_N)})


def ensure_min_families(
    t: FuzzTimeline, rng: random.Random, min_families: int = 3
) -> FuzzTimeline:
    """Mutation/crossover fixup: a searched timeline must keep composing
    at least `min_families` chaos families (the tier-1 smoke asserts 3).
    Checked-in reproducers are exempt — shrinking goes below on purpose."""
    missing = [f for f in FAMILIES if f not in t.families()]
    rng.shuffle(missing)
    while len(t.families()) < min_families and missing:
        t.events.append(_rand_event(rng, missing.pop()))
    return t


def seed_timeline(seed: int, n_events: int = 12) -> FuzzTimeline:
    """A baseline corpus entry: a deterministic event mix spanning at
    least three families, with flap batches closed by chunk events."""
    rng = random.Random(f"fuzz-seed:{seed}")
    fams = list(FAMILIES)
    rng.shuffle(fams)
    events: list[FuzzEvent] = []
    for k in range(n_events):
        fam = fams[k % len(fams)] if k < len(fams) else rng.choice(FAMILIES)
        events.append(_rand_event(rng, fam))
    # every flap batch coalesces at least once; one closing SPF check
    if any(e.family == "flap" for e in events):
        events.append(FuzzEvent("flap", "chunk", {}))
    events.append(FuzzEvent("engine", "spf", {"off": rng.randrange(_N)}))
    t = FuzzTimeline(seed=seed, events=events)
    return ensure_min_families(t, rng)


def mutate(t: FuzzTimeline, rng: random.Random) -> FuzzTimeline:
    """One mutation step: insert / delete / duplicate / retarget an
    event.  Returns a new timeline; the parent is never modified."""
    events = [FuzzEvent.from_json(e.to_json()) for e in t.events]
    op = rng.choice(("insert", "delete", "dup", "tweak"))
    if op == "insert" or not events:
        i = rng.randrange(len(events) + 1)
        events.insert(i, _rand_event(rng, rng.choice(FAMILIES)))
    elif op == "delete" and len(events) > 1:
        events.pop(rng.randrange(len(events)))
    elif op == "dup":
        i = rng.randrange(len(events))
        events.insert(i, FuzzEvent.from_json(events[i].to_json()))
    else:  # tweak: re-synthesize one event within its family
        i = rng.randrange(len(events))
        events[i] = _rand_event(rng, events[i].family)
    out = FuzzTimeline(seed=rng.randrange(1 << 30), events=events)
    FUZZ_COUNTERS.bump("chaos.fuzz.mutations")
    return ensure_min_families(out, rng)


def crossover(
    a: FuzzTimeline, b: FuzzTimeline, rng: random.Random
) -> FuzzTimeline:
    """One-point crossover: a prefix of `a` spliced onto a suffix of
    `b` — the operator that composes fault families that never met in
    either parent."""
    i = rng.randrange(len(a.events) + 1)
    j = rng.randrange(len(b.events) + 1)
    events = [
        FuzzEvent.from_json(e.to_json())
        for e in (a.events[:i] + b.events[j:])
    ]
    if not events:
        events = [_rand_event(rng, rng.choice(FAMILIES))]
    out = FuzzTimeline(seed=rng.randrange(1 << 30), events=events)
    FUZZ_COUNTERS.bump("chaos.fuzz.crossovers")
    return ensure_min_families(out, rng)


# -- the fuzz loop -----------------------------------------------------------


@dataclass
class FuzzSessionResult:
    seed: int
    requested: int
    results: list = field(default_factory=list)  # FuzzRunResult, run order
    corpus: list = field(default_factory=list)  # timelines that added coverage
    coverage_history: list = field(default_factory=list)  # cumulative |tokens|
    failures: list = field(default_factory=list)  # oracle-violating results
    shed: int = 0  # runs dropped by the wall budget
    sched_tokens: int = 0  # explorer tokens merged into the coverage map

    @property
    def coverage(self) -> int:
        return self.coverage_history[-1] if self.coverage_history else 0


def fuzz(
    n: int,
    seed: int = 0,
    budget_s: float = 0.0,
    plant: bool = False,
    crossover_p: float = 0.33,
    n_seeds: int = 3,
    stop_on_failure: bool = False,
    sched_n: int = 0,
) -> FuzzSessionResult:
    """Run `n` timelines: the seed corpus first, then mutants and
    crossovers of whatever earned corpus membership by novel coverage.

    `budget_s` > 0 bounds wall time: remaining runs are SHED LOUDLY
    (`result.shed`, stderr note) instead of letting a slow box time the
    whole suite out — the bench.py budget discipline.

    `sched_n` > 0 additionally samples that many schedules from the
    OPENR_SCHED explorer (analysis/sched.py) and merges their
    ``sched:<scenario>:<choice-fingerprint>`` tokens into this session's
    coverage map, so timeline search and schedule search share one
    novelty frontier: a timeline is only "novel" if it reaches state no
    explored schedule already witnessed, and vice versa."""
    rng = random.Random(seed)
    corpus = [seed_timeline(seed * 1000003 + i) for i in range(n_seeds)]
    session = FuzzSessionResult(seed=seed, requested=n)
    seen: set = set()
    if sched_n > 0:
        from ..analysis import sched as _sched

        sched_tokens = _sched.sample_tokens(seed, n_schedules=sched_n)
        if sched_tokens - seen:
            seen |= sched_tokens
            FUZZ_COUNTERS.bump("chaos.fuzz.novel_fingerprints")
        session.sched_tokens = len(sched_tokens)
    deadline = time.monotonic() + budget_s if budget_s > 0 else None
    for i in range(n):
        if deadline is not None and time.monotonic() > deadline:
            session.shed = n - i
            print(
                f"chaos.fuzz: wall budget {budget_s:.0f}s exhausted after "
                f"{i}/{n} runs; shedding {session.shed} "
                "(raise --budget-s or OPENR_FUZZ_BUDGET_S)",
                file=sys.stderr,
            )
            break
        if i < len(corpus):
            t = corpus[i]
        elif len(corpus) >= 2 and rng.random() < crossover_p:
            a, b = rng.sample(range(len(corpus)), 2)
            t = crossover(corpus[a], corpus[b], rng)
        else:
            t = mutate(corpus[rng.randrange(len(corpus))], rng)
        res = run_timeline(t, plant=plant)
        session.results.append(res)
        novel = res.fingerprint - seen
        if novel:
            seen |= novel
            FUZZ_COUNTERS.bump("chaos.fuzz.novel_fingerprints")
            if i >= len(corpus):
                corpus.append(t)
        session.coverage_history.append(len(seen))
        if not res.ok:
            FUZZ_COUNTERS.bump("chaos.fuzz.oracle_failures")
            session.failures.append(res)
            if stop_on_failure:
                break
    session.corpus = corpus
    return session


# -- the shrinker ------------------------------------------------------------


def shrink(
    timeline: FuzzTimeline,
    plant: bool = False,
    oracle: Optional[str] = None,
) -> FuzzTimeline:
    """Delta-debug an oracle-violating timeline down to a minimal
    reproducer: ddmin chunk removal (halving granularity) followed by a
    parameter-shrink pass.  Every candidate evaluation is one full
    deterministic replay (`chaos.fuzz.shrink_steps`)."""

    def violates(t: FuzzTimeline) -> Optional[str]:
        FUZZ_COUNTERS.bump("chaos.fuzz.shrink_steps")
        res = run_timeline(t, plant=plant)
        if not res.failures:
            return None
        if oracle is not None and oracle not in res.failures:
            return None
        return res.failures[0]

    first = violates(timeline)
    if first is None:
        raise ValueError(
            "shrink: the input timeline does not violate "
            f"{oracle or 'any oracle'} — nothing to reduce"
        )
    target = oracle or first

    events = list(timeline.events)
    gran = 2
    while len(events) > 1:
        chunk = -(-len(events) // gran)
        reduced = False
        for start in range(0, len(events), chunk):
            cand = events[:start] + events[start + chunk :]
            if not cand:
                continue
            t2 = FuzzTimeline(seed=timeline.seed, events=cand)
            if violates(t2) == target:
                events = cand
                gran = max(2, gran - 1)
                reduced = True
                break
        if not reduced:
            if gran >= len(events):
                break
            gran = min(len(events), 2 * gran)

    # parameter shrink: smaller storms / bursts when they still fail
    for i, ev in enumerate(events):
        for key, floor in (("n_keys", 1), ("q", 1)):
            v = ev.params.get(key)
            if isinstance(v, int) and v > floor:
                cand = [
                    FuzzEvent.from_json(e.to_json()) for e in events
                ]
                cand[i].params[key] = floor
                t2 = FuzzTimeline(seed=timeline.seed, events=cand)
                if violates(t2) == target:
                    events = cand
    return FuzzTimeline(
        seed=timeline.seed,
        events=events,
        oracle=target,
        note=f"shrunk from {len(timeline.events)} events",
    )


def shrink_preserving_coverage(
    timeline: FuzzTimeline, tokens: frozenset
) -> FuzzTimeline:
    """Same ddmin chunk-removal skeleton as `shrink`, but the predicate
    is coverage retention instead of oracle violation: a candidate
    survives iff it still replays clean AND its fingerprint covers
    `tokens`.  This is how clean-but-novel session timelines are
    minimized before being checked into tests/chaos_corpus/ — the entry
    keeps witnessing the exact coverage that earned it corpus
    membership, at a fraction of the replay cost."""

    def keeps(t: FuzzTimeline) -> bool:
        FUZZ_COUNTERS.bump("chaos.fuzz.shrink_steps")
        res = run_timeline(t)
        return res.ok and tokens <= res.fingerprint

    if not keeps(timeline):
        raise ValueError(
            "shrink_preserving_coverage: the input timeline does not "
            "cover the requested tokens cleanly — nothing to preserve"
        )
    events = list(timeline.events)
    gran = 2
    while len(events) > 1:
        chunk = -(-len(events) // gran)
        reduced = False
        for start in range(0, len(events), chunk):
            cand = events[:start] + events[start + chunk :]
            if not cand:
                continue
            if keeps(FuzzTimeline(seed=timeline.seed, events=cand)):
                events = cand
                gran = max(2, gran - 1)
                reduced = True
                break
        if not reduced:
            if gran >= len(events):
                break
            gran = min(len(events), 2 * gran)
    return FuzzTimeline(seed=timeline.seed, events=events)


# -- CLI ---------------------------------------------------------------------


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m openr_tpu.chaos.fuzz",
        description=__doc__.splitlines()[0],
    )
    parser.add_argument(
        "--fuzz-n", type=int, default=50, help="timelines to run"
    )
    parser.add_argument(
        "--seed",
        type=int,
        default=int(os.environ.get("OPENR_FUZZ_SEED", "0")),
        help="session seed (default: OPENR_FUZZ_SEED or 0)",
    )
    parser.add_argument(
        "--budget-s",
        type=float,
        default=float(os.environ.get("OPENR_FUZZ_BUDGET_S", "0")),
        help="wall budget; remaining runs shed loudly (0 = uncapped)",
    )
    parser.add_argument(
        "--shrink",
        metavar="ENTRY",
        help="shrink a failing corpus entry (JSON path) instead of fuzzing",
    )
    parser.add_argument(
        "--plant",
        action="store_true",
        default=os.environ.get("OPENR_FUZZ_PLANT", "0") == "1",
        help="arm the seeded ledger-misaccounting bug (shrinker self-test)",
    )
    parser.add_argument(
        "--out",
        default="chaos_corpus",
        help="directory for shrunk reproducers",
    )
    parser.add_argument(
        "--sched-n",
        type=int,
        default=0,
        help=(
            "sample this many OPENR_SCHED schedules and merge their "
            "coverage tokens into the session's novelty frontier"
        ),
    )
    args = parser.parse_args(argv)

    if args.shrink:
        with open(args.shrink) as fh:
            t = FuzzTimeline.loads(fh.read())
        minimal = shrink(t, plant=args.plant, oracle=t.oracle or None)
        out_path = args.shrink.rsplit(".json", 1)[0] + ".min.json"
        with open(out_path, "w") as fh:
            fh.write(minimal.dumps() + "\n")
        print(
            f"shrunk {len(t.events)} -> {len(minimal.events)} events "
            f"(oracle: {minimal.oracle}) -> {out_path}"
        )
        return 0

    session = fuzz(
        args.fuzz_n,
        seed=args.seed,
        budget_s=args.budget_s,
        plant=args.plant,
        sched_n=args.sched_n,
    )
    ran = len(session.results)
    print(
        f"chaos.fuzz: {ran}/{session.requested} runs "
        f"(seed={args.seed}, shed={session.shed}), "
        f"coverage={session.coverage} tokens "
        f"({session.sched_tokens} from sched), "
        f"corpus={len(session.corpus)}, "
        f"failures={len(session.failures)}"
    )
    if not session.failures:
        return 0
    os.makedirs(args.out, exist_ok=True)
    for k, res in enumerate(session.failures):
        minimal = shrink(
            res.timeline, plant=args.plant, oracle=res.failures[0]
        )
        path = os.path.join(
            args.out, f"fuzz_{args.seed}_{k}_{minimal.oracle}.json"
        )
        with open(path, "w") as fh:
            fh.write(minimal.dumps() + "\n")
        print(
            f"  failure {k}: {res.failures} -> {len(minimal.events)}-event "
            f"reproducer at {path}"
        )
    return 1


if __name__ == "__main__":
    sys.exit(main())
