"""Seeded, deterministic fault injection for multi-node tests.

One seed drives every fault decision (packet drop/duplicate/reorder/
delay, transport partitions, Fib agent crashes, KvStore sync failures),
so any chaos run replays bit-for-bit from its seed — the DeltaPath-style
churn-correctness proof machinery for this repo (see PAPERS.md).
"""

from .chaos import (
    ChaosEventLog,
    ChaosIoProvider,
    ChaosSpfBackend,
    FibChaosPlan,
    KvChaosInjector,
    LinkFaultProfile,
)
from .flapstorm import FlapStormResult, FlapStormScenario
from .ocs import OcsController, OcsRewireResult
from .overload import LoadReport, OpenLoopLoadGen
from .replicafleet import (
    ChaosReplicaHandle,
    ReplicaFleetController,
    ReplicaFleetResult,
)
from .scenario import (
    ChaosScenario,
    fib_unicast_routes,
    hold_converged,
    oracle_route_dbs,
)

__all__ = [
    "ChaosEventLog",
    "ChaosIoProvider",
    "ChaosReplicaHandle",
    "ChaosScenario",
    "ChaosSpfBackend",
    "FibChaosPlan",
    "FlapStormResult",
    "FlapStormScenario",
    "KvChaosInjector",
    "LinkFaultProfile",
    "LoadReport",
    "OcsController",
    "OcsRewireResult",
    "OpenLoopLoadGen",
    "ReplicaFleetController",
    "ReplicaFleetResult",
    "fib_unicast_routes",
    "hold_converged",
    "oracle_route_dbs",
]
