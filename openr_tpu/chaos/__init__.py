"""Seeded, deterministic fault injection for multi-node tests.

One seed drives every fault decision (packet drop/duplicate/reorder/
delay, transport partitions, Fib agent crashes, KvStore sync failures),
so any chaos run replays bit-for-bit from its seed — the DeltaPath-style
churn-correctness proof machinery for this repo (see PAPERS.md).
"""

from .chaos import (
    ChaosEventLog,
    ChaosIoProvider,
    ChaosSpfBackend,
    FibChaosPlan,
    KvChaosInjector,
    LinkFaultProfile,
    wait_timeout_scale,
)
from .flapstorm import FlapStormResult, FlapStormScenario

# NOTE: the fuzz *loop* stays addressed as openr_tpu.chaos.fuzz.fuzz —
# re-exporting the function here would shadow the submodule attribute
from .fuzz import (
    FUZZ_COUNTER_KEYS,
    FUZZ_COUNTERS,
    FuzzEvent,
    FuzzSessionResult,
    FuzzTimeline,
    run_timeline,
    seed_timeline,
    shrink,
)
from .ocs import OcsController, OcsRewireResult
from .overload import LoadReport, OpenLoopLoadGen
from .replicafleet import (
    ChaosReplicaHandle,
    ReplicaFleetController,
    ReplicaFleetResult,
)
from .scenario import (
    ChaosScenario,
    fib_unicast_routes,
    hold_converged,
    oracle_route_dbs,
)

__all__ = [
    "ChaosEventLog",
    "ChaosIoProvider",
    "ChaosReplicaHandle",
    "ChaosScenario",
    "ChaosSpfBackend",
    "FibChaosPlan",
    "FlapStormResult",
    "FlapStormScenario",
    "FUZZ_COUNTER_KEYS",
    "FUZZ_COUNTERS",
    "FuzzEvent",
    "FuzzSessionResult",
    "FuzzTimeline",
    "KvChaosInjector",
    "LinkFaultProfile",
    "LoadReport",
    "OcsController",
    "OcsRewireResult",
    "OpenLoopLoadGen",
    "ReplicaFleetController",
    "ReplicaFleetResult",
    "fib_unicast_routes",
    "hold_converged",
    "oracle_route_dbs",
    "run_timeline",
    "seed_timeline",
    "shrink",
    "wait_timeout_scale",
]
