"""rtnetlink codec + event socket, from scratch (no pyroute2 et al).

Reference surface reproduced (openr/nl/):
- message codec: NetlinkMessage framing (NetlinkMessage.h:39) for
  RTM_GETLINK / RTM_GETADDR dumps and RTM_NEWLINK / DELLINK / NEWADDR /
  DELADDR event parsing (NetlinkRoute.h:177 NetlinkLinkMessage, :214
  NetlinkAddrMessage)
- `NetlinkProtocolSocket` (NetlinkProtocolSocket.h:96): AF_NETLINK socket
  in its own event base, initial full dumps, kernel multicast-group
  subscription (RTMGRP_LINK + v4/v6 IFADDR), typed events pushed to the
  daemon's netlink-events queue — the producer the LinkMonitor dataflow
  starts from (SURVEY §1: netlink -> netlinkEventsQueue -> LinkMonitor).

Only the link/address surface is implemented natively; route programming
goes through the platform agent (openr_tpu.platform), which is this
framework's FibService boundary.
"""

from __future__ import annotations

import asyncio
import errno
import ipaddress
import socket
import struct
from dataclasses import dataclass
from typing import Iterator, Optional

from ..runtime.eventbase import OpenrEventBase
from ..runtime.queue import ReplicateQueue
from ..types import AddrEvent, LinkEvent

# netlink protocol constants (linux/netlink.h, linux/rtnetlink.h)
NETLINK_ROUTE = 0

NLMSG_NOOP = 1
NLMSG_ERROR = 2
NLMSG_DONE = 3

NLM_F_REQUEST = 0x01
NLM_F_MULTI = 0x02
NLM_F_ROOT = 0x100
NLM_F_MATCH = 0x200
NLM_F_DUMP = NLM_F_ROOT | NLM_F_MATCH

RTM_NEWLINK = 16
RTM_DELLINK = 17
RTM_GETLINK = 18
RTM_NEWADDR = 20
RTM_DELADDR = 21
RTM_GETADDR = 22
RTM_NEWROUTE = 24
RTM_DELROUTE = 25
RTM_GETROUTE = 26
RTM_NEWNEIGH = 28
RTM_DELNEIGH = 29
RTM_GETNEIGH = 30

NLM_F_CREATE = 0x400
NLM_F_REPLACE = 0x100
NLM_F_ACK = 0x04

RTMGRP_LINK = 0x1
RTMGRP_IPV4_IFADDR = 0x10
RTMGRP_IPV6_IFADDR = 0x100
RTMGRP_IPV4_ROUTE = 0x40
RTMGRP_IPV6_ROUTE = 0x400

IFF_UP = 0x1
IFF_RUNNING = 0x40

IFLA_IFNAME = 3
IFA_ADDRESS = 1
IFA_LOCAL = 2

# rtattr types for RTM_*ROUTE (linux/rtnetlink.h)
RTA_DST = 1
RTA_OIF = 4
RTA_GATEWAY = 5
RTA_PRIORITY = 6
RTA_MULTIPATH = 9
RTA_TABLE = 15
RTA_VIA = 18
RTA_NEWDST = 19
RTA_ENCAP_TYPE = 21
RTA_ENCAP = 22
# lwtunnel encap (linux/lwtunnel.h, linux/mpls_iptunnel.h) — label PUSH
# on IP routes rides an MPLS encap, exactly as the reference programs it
# (openr/nl/NetlinkRoute.cpp addNextHops push path)
LWTUNNEL_ENCAP_MPLS = 1
MPLS_IPTUNNEL_DST = 1

# ndattr types for RTM_*NEIGH (linux/neighbour.h)
NDA_DST = 1
NDA_LLADDR = 2

RT_TABLE_MAIN = 254
RT_SCOPE_UNIVERSE = 0
RT_SCOPE_LINK = 253
RTN_UNICAST = 1
# reference: openr's kernel route protocol id (Platform.thrift FibClient
# -> protocol mapping, openr/if/Platform.thrift:23; kRouteProtoId 99)
RTPROT_OPENR = 99

AF_MPLS = 28

_NLMSGHDR = struct.Struct("=IHHII")  # len, type, flags, seq, pid
_IFINFOMSG = struct.Struct("=BxHiII")  # family, type, index, flags, change
_IFADDRMSG = struct.Struct("=BBBBi")  # family, prefixlen, flags, scope, index
_RTMSG = struct.Struct("=BBBBBBBBI")  # family, dst_len, src_len, tos,
#   table, protocol, scope, type, flags
_RTNEXTHOP = struct.Struct("=HBBi")  # len, flags, hops (weight-1), ifindex
_NDMSG = struct.Struct("=BxxxiHBB")  # family, ifindex, state, flags, type
_RTATTR = struct.Struct("=HH")  # len, type
_GENMSG = struct.Struct("=Bxxx")  # rtgenmsg: family


class NetlinkError(OSError):
    pass


def _align4(n: int) -> int:
    return (n + 3) & ~3


def _walk_rtattrs(data: bytes) -> Iterator[tuple[int, bytes]]:
    """Yield (attr_type, payload) over an rtattr chain."""
    off = 0
    while off + _RTATTR.size <= len(data):
        alen, atype = _RTATTR.unpack_from(data, off)
        if alen < _RTATTR.size:
            return
        yield atype, data[off + _RTATTR.size : off + alen]
        off += _align4(alen)


@dataclass(slots=True)
class LinkInfo:
    """Reference: openr::fbnl::Link (NetlinkTypes.h)."""

    if_index: int
    if_name: str
    flags: int

    @property
    def is_up(self) -> bool:
        return bool(self.flags & IFF_UP)


@dataclass(slots=True)
class AddrInfo:
    """Reference: openr::fbnl::IfAddress (NetlinkTypes.h)."""

    if_index: int
    family: int
    prefix: str  # CIDR
    is_valid: bool = True  # False for RTM_DELADDR


@dataclass(slots=True)
class NextHopInfo:
    """One path of a (possibly multipath) kernel route
    (reference: openr::fbnl::NextHop, NetlinkTypes.h:48).

    `push_labels` (IP routes): MPLS encap label stack (RTA_ENCAP).
    `swap_labels` (AF_MPLS routes): outgoing stack (RTA_NEWDST); an MPLS
    nexthop without swap_labels pops the top label (PHP/POP)."""

    gateway: Optional[str] = None  # ip address string
    if_index: int = 0
    weight: int = 1  # rtnh_hops + 1
    push_labels: tuple = ()  # lwtunnel MPLS encap (IP routes)
    swap_labels: tuple = ()  # RTA_NEWDST (MPLS routes)


@dataclass(slots=True)
class MplsRouteInfo:
    """Kernel AF_MPLS label route (reference: NetlinkRouteMessage MPLS
    parse/build, openr/nl/NetlinkRoute.h:41-176; label stacks in
    NetlinkTypes.h:48-285).  Nexthop gateways ride RTA_VIA."""

    label: int
    protocol: int = RTPROT_OPENR
    nexthops: list[NextHopInfo] = None  # type: ignore[assignment]

    def __post_init__(self) -> None:
        if self.nexthops is None:
            self.nexthops = []


@dataclass(slots=True)
class RouteInfo:
    """Kernel unicast route (reference: openr::fbnl::Route,
    NetlinkTypes.h:141; message codec NetlinkRoute.h:41)."""

    dst: str  # CIDR
    family: int = socket.AF_INET6
    table: int = RT_TABLE_MAIN
    protocol: int = RTPROT_OPENR
    scope: int = RT_SCOPE_UNIVERSE
    rtype: int = RTN_UNICAST
    priority: Optional[int] = None
    nexthops: list[NextHopInfo] = None  # type: ignore[assignment]

    def __post_init__(self) -> None:
        if self.nexthops is None:
            self.nexthops = []


@dataclass(slots=True)
class NeighborInfo:
    """Kernel neighbor entry (reference: NetlinkNeighborMessage,
    NetlinkRoute.h:255; openr::fbnl::Neighbor)."""

    if_index: int
    family: int
    dst: str
    lladdr: Optional[str] = None
    state: int = 0


@dataclass(slots=True)
class NetlinkMsg:
    msg_type: int
    link: Optional[LinkInfo] = None
    addr: Optional[AddrInfo] = None
    route: Optional[RouteInfo] = None
    mpls_route: Optional[MplsRouteInfo] = None
    neigh: Optional[NeighborInfo] = None
    error: int = 0
    # header identity, so request/reply correlation can reject stray or
    # late messages on shared request sockets (advisor r3)
    seq: int = 0
    pid: int = 0


def _parse_link(payload: bytes) -> LinkInfo:
    family, _type, index, flags, _change = _IFINFOMSG.unpack_from(payload, 0)
    name = ""
    for atype, adata in _walk_rtattrs(payload[_IFINFOMSG.size :]):
        if atype == IFLA_IFNAME:
            name = adata.rstrip(b"\x00").decode()
    return LinkInfo(if_index=index, if_name=name, flags=flags)


def _parse_addr(payload: bytes, deleted: bool) -> Optional[AddrInfo]:
    family, prefixlen, _flags, _scope, index = _IFADDRMSG.unpack_from(
        payload, 0
    )
    address: Optional[bytes] = None
    local: Optional[bytes] = None
    for atype, adata in _walk_rtattrs(payload[_IFADDRMSG.size :]):
        if atype == IFA_ADDRESS:
            address = adata
        elif atype == IFA_LOCAL:
            local = adata
    raw = local or address  # IFA_LOCAL is the interface address on v4 ptp
    if raw is None:
        return None
    try:
        ip = ipaddress.ip_address(raw)
    except ValueError:
        return None
    return AddrInfo(
        if_index=index,
        family=family,
        prefix=f"{ip}/{prefixlen}",
        is_valid=not deleted,
    )


def _rtattr(atype: int, payload: bytes) -> bytes:
    alen = _RTATTR.size + len(payload)
    return _RTATTR.pack(alen, atype) + payload + b"\x00" * (
        _align4(alen) - alen
    )


# -- MPLS label-stack wire format (RFC 3032 entries, linux/mpls.h) ----------


def pack_label_stack(labels: tuple) -> bytes:
    """Label stack entries, 32-bit BE each: label<<12 | tc<<9 | bos<<8 |
    ttl; bottom-of-stack set on the last entry (reference label encode:
    NetlinkRoute.cpp encodeLabel)."""
    out = b""
    for i, label in enumerate(labels):
        bos = 1 if i == len(labels) - 1 else 0
        out += struct.pack(">I", (int(label) << 12) | (bos << 8))
    return out


def unpack_label_stack(data: bytes) -> tuple:
    labels = []
    for off in range(0, len(data) - 3, 4):
        (entry,) = struct.unpack_from(">I", data, off)
        labels.append(entry >> 12)
        if entry & 0x100:  # bottom of stack
            break
    return tuple(labels)


def _pack_via(gateway: str) -> bytes:
    """struct rtvia: u16 family + packed address (RTA_VIA)."""
    ip = ipaddress.ip_address(gateway)
    family = socket.AF_INET if ip.version == 4 else socket.AF_INET6
    return struct.pack("=H", family) + ip.packed


def _unpack_via(data: bytes) -> Optional[str]:
    if len(data) < 2:
        return None
    try:
        return str(ipaddress.ip_address(data[2:]))
    except ValueError:
        return None


def _pack_mpls_encap(push_labels: tuple) -> bytes:
    """RTA_ENCAP_TYPE=MPLS + nested RTA_ENCAP{MPLS_IPTUNNEL_DST} — label
    PUSH on an IP route (reference: NetlinkRoute.cpp push encap)."""
    return _rtattr(
        RTA_ENCAP_TYPE, struct.pack("=H", LWTUNNEL_ENCAP_MPLS)
    ) + _rtattr(
        RTA_ENCAP, _rtattr(MPLS_IPTUNNEL_DST, pack_label_stack(push_labels))
    )


def _parse_mpls_encap(encap_type: Optional[int], encap: Optional[bytes]) -> tuple:
    if encap_type != LWTUNNEL_ENCAP_MPLS or not encap:
        return ()
    for satype, sadata in _walk_rtattrs(encap):
        if satype == MPLS_IPTUNNEL_DST:
            return unpack_label_stack(sadata)
    return ()


def _parse_route(payload: bytes) -> Optional[RouteInfo]:
    family, dst_len, _src_len, _tos, table, protocol, scope, rtype, _flags = (
        _RTMSG.unpack_from(payload, 0)
    )
    dst_bytes: Optional[bytes] = None
    gateway: Optional[bytes] = None
    oif = 0
    priority: Optional[int] = None
    multipath: list[NextHopInfo] = []
    encap_type: Optional[int] = None
    encap: Optional[bytes] = None
    for atype, adata in _walk_rtattrs(payload[_RTMSG.size :]):
        if atype == RTA_DST:
            dst_bytes = adata
        elif atype == RTA_GATEWAY:
            gateway = adata
        elif atype == RTA_OIF:
            (oif,) = struct.unpack("=i", adata)
        elif atype == RTA_PRIORITY:
            (priority,) = struct.unpack("=I", adata)
        elif atype == RTA_TABLE:
            (table,) = struct.unpack("=I", adata)
        elif atype == RTA_ENCAP_TYPE:
            (encap_type,) = struct.unpack_from("=H", adata, 0)
        elif atype == RTA_ENCAP:
            encap = adata
        elif atype == RTA_MULTIPATH:
            off = 0
            while off + _RTNEXTHOP.size <= len(adata):
                rlen, _rflags, hops, ifindex = _RTNEXTHOP.unpack_from(
                    adata, off
                )
                if rlen < _RTNEXTHOP.size:
                    break
                gw: Optional[str] = None
                sub_encap_type: Optional[int] = None
                sub_encap: Optional[bytes] = None
                for satype, sadata in _walk_rtattrs(
                    adata[off + _RTNEXTHOP.size : off + rlen]
                ):
                    if satype == RTA_GATEWAY:
                        try:
                            gw = str(ipaddress.ip_address(sadata))
                        except ValueError:
                            pass
                    elif satype == RTA_ENCAP_TYPE:
                        (sub_encap_type,) = struct.unpack_from(
                            "=H", sadata, 0
                        )
                    elif satype == RTA_ENCAP:
                        sub_encap = sadata
                multipath.append(
                    NextHopInfo(
                        gateway=gw,
                        if_index=ifindex,
                        weight=hops + 1,
                        push_labels=_parse_mpls_encap(
                            sub_encap_type, sub_encap
                        ),
                    )
                )
                off += _align4(rlen)
    if family not in (socket.AF_INET, socket.AF_INET6):
        return None  # AF_MPLS rides _parse_mpls_route
    if dst_bytes is not None:
        try:
            ip = ipaddress.ip_address(dst_bytes)
        except ValueError:
            return None
        dst = f"{ip}/{dst_len}"
    elif dst_len == 0:  # default route carries no RTA_DST
        dst = "0.0.0.0/0" if family == socket.AF_INET else "::/0"
    else:
        return None
    nexthops = multipath
    if not nexthops and (gateway is not None or oif):
        gw = None
        if gateway is not None:
            try:
                gw = str(ipaddress.ip_address(gateway))
            except ValueError:
                gw = None
        nexthops = [
            NextHopInfo(
                gateway=gw,
                if_index=oif,
                push_labels=_parse_mpls_encap(encap_type, encap),
            )
        ]
    return RouteInfo(
        dst=dst,
        family=family,
        table=table,
        protocol=protocol,
        scope=scope,
        rtype=rtype,
        priority=priority,
        nexthops=nexthops,
    )


def _parse_mpls_route(payload: bytes) -> Optional[MplsRouteInfo]:
    """Decode an AF_MPLS RTM_NEWROUTE: incoming label (RTA_DST label
    entry), per-nexthop RTA_VIA gateway + RTA_NEWDST outgoing stack
    (reference route parse: openr/nl/NetlinkRoute.h:41-176,
    parseRoute/parseNextHops MPLS branches)."""
    (
        family,
        _dst_len,
        _src_len,
        _tos,
        _table,
        protocol,
        _scope,
        _rtype,
        _flags,
    ) = _RTMSG.unpack_from(payload, 0)
    if family != AF_MPLS:
        return None
    label: Optional[int] = None
    via: Optional[str] = None
    oif = 0
    newdst: tuple = ()
    multipath: list[NextHopInfo] = []
    for atype, adata in _walk_rtattrs(payload[_RTMSG.size :]):
        if atype == RTA_DST:
            stack = unpack_label_stack(adata)
            label = stack[0] if stack else None
        elif atype == RTA_VIA:
            via = _unpack_via(adata)
        elif atype == RTA_OIF:
            (oif,) = struct.unpack("=i", adata)
        elif atype == RTA_NEWDST:
            newdst = unpack_label_stack(adata)
        elif atype == RTA_MULTIPATH:
            off = 0
            while off + _RTNEXTHOP.size <= len(adata):
                rlen, _rflags, hops, ifindex = _RTNEXTHOP.unpack_from(
                    adata, off
                )
                if rlen < _RTNEXTHOP.size:
                    break
                sub_via: Optional[str] = None
                sub_newdst: tuple = ()
                for satype, sadata in _walk_rtattrs(
                    adata[off + _RTNEXTHOP.size : off + rlen]
                ):
                    if satype == RTA_VIA:
                        sub_via = _unpack_via(sadata)
                    elif satype == RTA_NEWDST:
                        sub_newdst = unpack_label_stack(sadata)
                multipath.append(
                    NextHopInfo(
                        gateway=sub_via,
                        if_index=ifindex,
                        weight=hops + 1,
                        swap_labels=sub_newdst,
                    )
                )
                off += _align4(rlen)
    if label is None:
        return None
    nexthops = multipath or [
        NextHopInfo(gateway=via, if_index=oif, swap_labels=newdst)
    ]
    return MplsRouteInfo(label=label, protocol=protocol, nexthops=nexthops)


def build_mpls_route_request(
    msg_type: int, seq: int, route: MplsRouteInfo
) -> bytes:
    """RTM_NEWROUTE / RTM_DELROUTE for an AF_MPLS label route
    (reference: NetlinkRouteMessage MPLS build, NetlinkRoute.h:41-176).
    A nexthop with swap_labels emits RTA_NEWDST (SWAP); without, the
    kernel pops the top label (PHP/POP — POP_AND_LOOKUP is oif-only)."""
    if msg_type == RTM_NEWROUTE:
        flags = NLM_F_REQUEST | NLM_F_ACK | NLM_F_CREATE | NLM_F_REPLACE
    else:
        flags = NLM_F_REQUEST | NLM_F_ACK
    attrs = _rtattr(RTA_DST, pack_label_stack((route.label,)))

    def nh_attrs(nh: NextHopInfo) -> bytes:
        sub = b""
        if nh.gateway is not None:
            sub += _rtattr(RTA_VIA, _pack_via(nh.gateway))
        if nh.swap_labels:
            sub += _rtattr(RTA_NEWDST, pack_label_stack(nh.swap_labels))
        return sub

    if len(route.nexthops) == 1:
        nh = route.nexthops[0]
        attrs += nh_attrs(nh)
        if nh.if_index:
            attrs += _rtattr(RTA_OIF, struct.pack("=i", nh.if_index))
    elif len(route.nexthops) > 1:
        blob = b""
        for nh in route.nexthops:
            sub = nh_attrs(nh)
            rlen = _RTNEXTHOP.size + len(sub)
            blob += (
                _RTNEXTHOP.pack(rlen, 0, max(nh.weight, 1) - 1, nh.if_index)
                + sub
            )
        attrs += _rtattr(RTA_MULTIPATH, blob)
    body = _RTMSG.pack(
        AF_MPLS,
        20,  # dst_len: one 20-bit label
        0,
        0,
        RT_TABLE_MAIN,
        route.protocol,
        RT_SCOPE_UNIVERSE,
        RTN_UNICAST,
        0,
    ) + attrs
    length = _NLMSGHDR.size + len(body)
    return _NLMSGHDR.pack(length, msg_type, flags, seq, 0) + body


def build_neigh_request(
    msg_type: int,
    seq: int,
    if_index: int,
    dst: str,
    lladdr: Optional[str] = None,
    state: int = 0x80,  # NUD_PERMANENT
) -> bytes:
    """RTM_NEWNEIGH / RTM_DELNEIGH (reference: NetlinkNeighborMessage,
    openr/nl/NetlinkRoute.h:255; builder NetlinkTypes.h:48-285)."""
    ip = ipaddress.ip_address(dst)
    family = socket.AF_INET if ip.version == 4 else socket.AF_INET6
    if msg_type == RTM_NEWNEIGH:
        flags = NLM_F_REQUEST | NLM_F_ACK | NLM_F_CREATE | NLM_F_REPLACE
    else:
        flags = NLM_F_REQUEST | NLM_F_ACK
        state = 0
    body = _NDMSG.pack(family, if_index, state, 0, 0) + _rtattr(
        NDA_DST, ip.packed
    )
    if lladdr is not None and msg_type == RTM_NEWNEIGH:
        body += _rtattr(
            NDA_LLADDR, bytes(int(b, 16) for b in lladdr.split(":"))
        )
    length = _NLMSGHDR.size + len(body)
    return _NLMSGHDR.pack(length, msg_type, flags, seq, 0) + body


def _parse_neigh(payload: bytes) -> Optional[NeighborInfo]:
    family, ifindex, state, _flags, _ntype = _NDMSG.unpack_from(payload, 0)
    dst: Optional[str] = None
    lladdr: Optional[str] = None
    for atype, adata in _walk_rtattrs(payload[_NDMSG.size :]):
        if atype == NDA_DST:
            try:
                dst = str(ipaddress.ip_address(adata))
            except ValueError:
                return None
        elif atype == NDA_LLADDR:
            lladdr = ":".join(f"{b:02x}" for b in adata)
    if dst is None:
        return None
    return NeighborInfo(
        if_index=ifindex, family=family, dst=dst, lladdr=lladdr, state=state
    )


def build_addr_request(
    msg_type: int, seq: int, if_index: int, prefix: str
) -> bytes:
    """RTM_NEWADDR / RTM_DELADDR for `prefix` (CIDR interface address)
    on interface `if_index` (reference: NetlinkAddrMessage,
    openr/nl/NetlinkRoute.h:214 — the PrefixAllocator's address-sync
    path)."""
    iface = ipaddress.ip_interface(prefix)
    family = socket.AF_INET if iface.version == 4 else socket.AF_INET6
    flags = (
        NLM_F_REQUEST | NLM_F_ACK | NLM_F_CREATE | NLM_F_REPLACE
        if msg_type == RTM_NEWADDR
        else NLM_F_REQUEST | NLM_F_ACK
    )
    packed = iface.ip.packed
    body = (
        _IFADDRMSG.pack(family, iface.network.prefixlen, 0, 0, if_index)
        + _rtattr(IFA_LOCAL, packed)
        + _rtattr(IFA_ADDRESS, packed)
    )
    length = _NLMSGHDR.size + len(body)
    return _NLMSGHDR.pack(length, msg_type, flags, seq, 0) + body


def build_route_request(
    msg_type: int, seq: int, route: RouteInfo, flags: Optional[int] = None
) -> bytes:
    """RTM_NEWROUTE / RTM_DELROUTE with RTA_DST and either a single
    RTA_GATEWAY/RTA_OIF or an RTA_MULTIPATH of rtnexthop entries
    (reference: NetlinkRouteMessage::init + addNextHops,
    openr/nl/NetlinkRoute.cpp:70-310)."""
    if flags is None:
        flags = (
            NLM_F_REQUEST | NLM_F_ACK | NLM_F_CREATE | NLM_F_REPLACE
            if msg_type == RTM_NEWROUTE
            else NLM_F_REQUEST | NLM_F_ACK
        )
    net = ipaddress.ip_network(route.dst)
    family = socket.AF_INET if net.version == 4 else socket.AF_INET6
    attrs = _rtattr(RTA_DST, net.network_address.packed)
    if route.table >= 256:
        # rtm_table is 8-bit; larger ids ride the RTA_TABLE attribute
        # with rtm_table = RT_TABLE_UNSPEC (rtnetlink convention)
        attrs += _rtattr(RTA_TABLE, struct.pack("=I", route.table))
    if route.priority is not None:
        attrs += _rtattr(RTA_PRIORITY, struct.pack("=I", route.priority))
    if len(route.nexthops) == 1:
        nh = route.nexthops[0]
        if nh.gateway is not None:
            attrs += _rtattr(
                RTA_GATEWAY, ipaddress.ip_address(nh.gateway).packed
            )
        if nh.if_index:
            attrs += _rtattr(RTA_OIF, struct.pack("=i", nh.if_index))
        if nh.push_labels:
            attrs += _pack_mpls_encap(nh.push_labels)
    elif len(route.nexthops) > 1:
        blob = b""
        for nh in route.nexthops:
            sub = b""
            if nh.gateway is not None:
                sub = _rtattr(
                    RTA_GATEWAY, ipaddress.ip_address(nh.gateway).packed
                )
            if nh.push_labels:
                sub += _pack_mpls_encap(nh.push_labels)
            rlen = _RTNEXTHOP.size + len(sub)
            blob += (
                _RTNEXTHOP.pack(rlen, 0, max(nh.weight, 1) - 1, nh.if_index)
                + sub
            )
        attrs += _rtattr(RTA_MULTIPATH, blob)
    body = _RTMSG.pack(
        family,
        net.prefixlen,
        0,
        0,
        route.table if route.table < 256 else 0,  # 0 + RTA_TABLE above
        route.protocol,
        route.scope,
        route.rtype,
        0,
    ) + attrs
    length = _NLMSGHDR.size + len(body)
    return _NLMSGHDR.pack(length, msg_type, flags, seq, 0) + body


def parse_messages(data: bytes) -> Iterator[NetlinkMsg]:
    """Parse a datagram of (possibly multipart) netlink messages."""
    off = 0
    while off + _NLMSGHDR.size <= len(data):
        mlen, mtype, _flags, seq, pid = _NLMSGHDR.unpack_from(data, off)
        if mlen < _NLMSGHDR.size or off + mlen > len(data):
            return
        payload = data[off + _NLMSGHDR.size : off + mlen]
        if mtype == NLMSG_DONE:
            yield NetlinkMsg(msg_type=NLMSG_DONE, seq=seq, pid=pid)
        elif mtype == NLMSG_ERROR:
            (errno_neg,) = struct.unpack_from("=i", payload, 0)
            yield NetlinkMsg(
                msg_type=NLMSG_ERROR, error=-errno_neg, seq=seq, pid=pid
            )
        elif mtype in (RTM_NEWLINK, RTM_DELLINK):
            yield NetlinkMsg(msg_type=mtype, link=_parse_link(payload))
        elif mtype in (RTM_NEWADDR, RTM_DELADDR):
            addr = _parse_addr(payload, deleted=mtype == RTM_DELADDR)
            if addr is not None:
                yield NetlinkMsg(msg_type=mtype, addr=addr)
        elif mtype in (RTM_NEWROUTE, RTM_DELROUTE):
            if payload[:1] == bytes([AF_MPLS]):
                mr = _parse_mpls_route(payload)
                if mr is not None:
                    yield NetlinkMsg(msg_type=mtype, mpls_route=mr)
            else:
                route = _parse_route(payload)
                if route is not None:
                    yield NetlinkMsg(msg_type=mtype, route=route)
        elif mtype in (RTM_NEWNEIGH, RTM_DELNEIGH):
            neigh = _parse_neigh(payload)
            if neigh is not None:
                yield NetlinkMsg(msg_type=mtype, neigh=neigh)
        off += _align4(mlen)


def build_dump_request(msg_type: int, seq: int, family: int = 0) -> bytes:
    """RTM_GETLINK / RTM_GETADDR full-dump request
    (reference: NetlinkLinkMessage::init dump flags)."""
    length = _NLMSGHDR.size + _GENMSG.size
    return _NLMSGHDR.pack(
        length, msg_type, NLM_F_REQUEST | NLM_F_DUMP, seq, 0
    ) + _GENMSG.pack(family)


class NetlinkProtocolSocket(OpenrEventBase):
    """Kernel link/address mirror + event subscription
    (reference: NetlinkProtocolSocket, NetlinkProtocolSocket.h:96; owned
    by its own event base per Main.cpp:330-343).

    Pushes LinkEvent/AddrEvent into `netlink_events_queue` — first a full
    synthetic replay of current kernel state (so LinkMonitor starts from
    truth), then live kernel multicast events."""

    def __init__(
        self,
        netlink_events_queue: Optional[ReplicateQueue] = None,
        groups: int = RTMGRP_LINK | RTMGRP_IPV4_IFADDR | RTMGRP_IPV6_IFADDR,
    ) -> None:
        super().__init__(name="netlink")
        self.netlink_events_queue = netlink_events_queue
        self._groups = groups
        self._sock: Optional[socket.socket] = None
        self._req_sock: Optional[socket.socket] = None
        self._seq = 0
        self.links: dict[int, LinkInfo] = {}  # kernel mirror by ifindex
        self.counters: dict[str, int] = {}

    def _bump(self, counter: str, n: int = 1) -> None:
        self.counters[counter] = self.counters.get(counter, 0) + n

    # -- synchronous dump API (reference: getAllLinks/getAllIfAddresses) ----

    def _dump(self, msg_type: int, family: int = 0) -> list[NetlinkMsg]:
        """One blocking dump request/response on a throwaway socket."""
        self._seq += 1
        sock = socket.socket(socket.AF_NETLINK, socket.SOCK_RAW, NETLINK_ROUTE)
        try:
            sock.bind((0, 0))
            sock.settimeout(5.0)
            sock.send(build_dump_request(msg_type, self._seq, family))
            out: list[NetlinkMsg] = []
            while True:
                data = sock.recv(65536)
                done = False
                for msg in parse_messages(data):
                    if msg.msg_type == NLMSG_DONE:
                        done = True
                        break
                    if msg.msg_type == NLMSG_ERROR and msg.error:
                        raise NetlinkError(msg.error, "netlink dump error")
                    out.append(msg)
                if done:
                    return out
        finally:
            sock.close()

    def get_all_links(self) -> list[LinkInfo]:
        return [m.link for m in self._dump(RTM_GETLINK) if m.link]

    def get_all_addresses(self) -> list[AddrInfo]:
        return [m.addr for m in self._dump(RTM_GETADDR) if m.addr]

    def get_all_neighbors(self) -> list[NeighborInfo]:
        """Reference: NetlinkProtocolSocket::getAllNeighbors
        (NetlinkProtocolSocket.h:96 surface)."""
        return [m.neigh for m in self._dump(RTM_GETNEIGH) if m.neigh]

    def get_routes(
        self,
        protocol: Optional[int] = RTPROT_OPENR,
        table: Optional[int] = RT_TABLE_MAIN,
    ) -> list[RouteInfo]:
        """Full route-table dump, filtered client-side by protocol/table
        (reference: NetlinkProtocolSocket::getRoutes / getAllRoutes;
        getRouteTableByClient reads back exactly the openr-protocol
        routes, openr/platform/NetlinkFibHandler.h)."""
        out = []
        for m in self._dump(RTM_GETROUTE):
            r = m.route
            if r is None:
                continue
            if protocol is not None and r.protocol != protocol:
                continue
            if table is not None and r.table != table:
                continue
            out.append(r)
        return out

    def get_mpls_routes(
        self, protocol: Optional[int] = RTPROT_OPENR
    ) -> list[MplsRouteInfo]:
        """AF_MPLS label-route dump, protocol-filtered — the kernel
        readback behind get_mpls_route_table_by_client (reference:
        NetlinkProtocolSocket::getMplsRoutes,
        openr/platform/NetlinkFibHandler.cpp getMplsRouteTableByClient)."""
        out = []
        for m in self._dump(RTM_GETROUTE, family=AF_MPLS):
            r = m.mpls_route
            if r is None:
                continue
            if protocol is not None and r.protocol != protocol:
                continue
            out.append(r)
        return out

    # -- synchronous route programming (reference: NetlinkRouteMessage
    # -- add/delete with ACK, openr/nl/NetlinkRoute.cpp) -------------------

    def _request_sock(self) -> socket.socket:
        """Persistent request socket for route transactions (the
        reference keeps one request fd too; a 1k-route sync must not pay
        1k socket setup/teardown cycles)."""
        if self._req_sock is None:
            sock = socket.socket(
                socket.AF_NETLINK, socket.SOCK_RAW, NETLINK_ROUTE
            )
            sock.bind((0, 0))
            sock.settimeout(5.0)
            self._req_sock = sock
        return self._req_sock

    def _transact(self, request: bytes) -> None:
        """Send one ACK-flagged request and wait for ITS NLMSG_ERROR
        (error 0 == ACK); raises NetlinkError on kernel rejection.

        Replies are matched on nlmsg_seq (and pid, when the kernel
        stamps one) against the outstanding request — a stray or late
        message on the persistent socket must not be misattributed as
        this request's verdict (advisor r3)."""
        sock = self._request_sock()
        own_pid = sock.getsockname()[0]
        try:
            sock.send(request)
            while True:
                data = sock.recv(65536)
                for msg in parse_messages(data):
                    if msg.msg_type != NLMSG_ERROR:
                        continue
                    if msg.seq != self._seq or (
                        msg.pid not in (0, own_pid)
                    ):
                        continue  # not ours: late reply from a prior seq
                    if msg.error:
                        raise NetlinkError(
                            msg.error, "netlink route request rejected"
                        )
                    return
        except NetlinkError:
            raise  # clean kernel rejection: the socket is still in sync
        except OSError:
            # timeout/desync: drop the socket so the next transact starts
            # from a clean fd + sequence space
            try:
                sock.close()
            finally:
                self._req_sock = None
            raise

    def add_route(self, route: RouteInfo) -> None:
        self._seq += 1
        self._transact(build_route_request(RTM_NEWROUTE, self._seq, route))
        self._bump("netlink.routes_added")

    def del_route(self, route: RouteInfo) -> None:
        self._seq += 1
        self._transact(build_route_request(RTM_DELROUTE, self._seq, route))
        self._bump("netlink.routes_deleted")

    def add_mpls_route(self, route: MplsRouteInfo) -> None:
        self._seq += 1
        self._transact(
            build_mpls_route_request(RTM_NEWROUTE, self._seq, route)
        )
        self._bump("netlink.mpls_routes_added")

    def del_mpls_route(self, route: MplsRouteInfo) -> None:
        self._seq += 1
        self._transact(
            build_mpls_route_request(RTM_DELROUTE, self._seq, route)
        )
        self._bump("netlink.mpls_routes_deleted")

    def add_neighbor(
        self, if_index: int, dst: str, lladdr: str, state: int = 0x80
    ) -> None:
        """Program a kernel neighbor entry (RTM_NEWNEIGH; default state
        NUD_PERMANENT).  Reference: NetlinkNeighborMessage,
        openr/nl/NetlinkRoute.h:255 + NeighborBuilder
        (openr/nl/NetlinkTypes.h:48-285)."""
        self._seq += 1
        self._transact(
            build_neigh_request(
                RTM_NEWNEIGH, self._seq, if_index, dst, lladdr, state
            )
        )
        self._bump("netlink.neighbors_added")

    def del_neighbor(self, if_index: int, dst: str) -> None:
        self._seq += 1
        self._transact(
            build_neigh_request(RTM_DELNEIGH, self._seq, if_index, dst)
        )
        self._bump("netlink.neighbors_deleted")

    def close_request_socket(self) -> None:
        """Release the persistent request fd (for codec-only users that
        never run the event base and so never hit stop())."""
        if self._req_sock is not None:
            try:
                self._req_sock.close()
            finally:
                self._req_sock = None

    def add_addr(self, if_index: int, prefix: str) -> None:
        """Assign an interface address (reference: NetlinkAddrMessage /
        PrefixAllocator address sync)."""
        self._seq += 1
        self._transact(
            build_addr_request(RTM_NEWADDR, self._seq, if_index, prefix)
        )
        self._bump("netlink.addrs_added")

    def del_addr(self, if_index: int, prefix: str) -> None:
        self._seq += 1
        self._transact(
            build_addr_request(RTM_DELADDR, self._seq, if_index, prefix)
        )
        self._bump("netlink.addrs_deleted")

    # -- event subscription --------------------------------------------------

    def run(self) -> None:
        super().run()
        self.wait_until_running()
        self.run_in_event_base_thread(self._setup).result()

    # reference: kNetlinkSockRecvBuf (NetlinkProtocolSocket.cpp:111-114)
    # — a large receive buffer so link/addr event storms don't overflow
    # the socket before the event loop drains it
    RCVBUF_SIZE = 1 << 20

    def _setup(self) -> None:
        sock = socket.socket(socket.AF_NETLINK, socket.SOCK_RAW, NETLINK_ROUTE)
        # SO_RCVBUFFORCE (=33, not in the socket module) needs
        # CAP_NET_ADMIN; fall back to the rlimit-capped SO_RCVBUF
        try:
            sock.setsockopt(socket.SOL_SOCKET, 33, self.RCVBUF_SIZE)
        except OSError:
            sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_RCVBUF, self.RCVBUF_SIZE
            )
        sock.bind((0, self._groups))
        sock.setblocking(False)
        self._sock = sock

        self._resync()
        self._loop.add_reader(sock.fileno(), self._on_readable)

    def _resync(self) -> None:
        """Full kernel-state replay: links first, then addresses
        (LinkMonitor needs the link before its addresses; reference does
        the same ordered bootstrap).  Also the ENOBUFS recovery path —
        when the kernel drops events the mirror is stale, so re-dump and
        replay everything (LinkEvent/AddrEvent replays are idempotent
        downstream, same as the initial bootstrap)."""
        self.links = {}
        for link in self.get_all_links():
            self.links[link.if_index] = link
            self._push(LinkEvent(link.if_name, link.if_index, link.is_up))
            self._bump("netlink.links")
        for addr in self.get_all_addresses():
            link = self.links.get(addr.if_index)
            if link is None:
                continue
            self._push(AddrEvent(link.if_name, addr.prefix, addr.is_valid))
            self._bump("netlink.addrs")

    def _on_readable(self) -> None:
        try:
            data = self._sock.recv(65536)
        except BlockingIOError:
            return
        except OSError as exc:
            if exc.errno == errno.ENOBUFS:
                # kernel dropped events: the mirror may have missed
                # link/addr transitions — discard whatever stale
                # pre-overflow events are still queued (they would
                # otherwise be applied on top of the fresh dump), then
                # resynchronize from a full dump (reference enlarges the
                # buffer and logs; we additionally recover the lost state)
                self._bump("netlink.enobufs")
                while True:
                    try:
                        self._sock.recv(65536)
                    except OSError:
                        break
                self._resync()
            return
        for msg in parse_messages(data):
            self._bump("netlink.events")
            if msg.link is not None:
                link = msg.link
                if msg.msg_type == RTM_DELLINK:
                    self.links.pop(link.if_index, None)
                    self._push(LinkEvent(link.if_name, link.if_index, False))
                else:
                    prev = self.links.get(link.if_index)
                    self.links[link.if_index] = link
                    if prev is None or prev.is_up != link.is_up:
                        self._push(
                            LinkEvent(link.if_name, link.if_index, link.is_up)
                        )
            elif msg.addr is not None:
                link = self.links.get(msg.addr.if_index)
                if link is None:
                    continue
                self._push(
                    AddrEvent(link.if_name, msg.addr.prefix, msg.addr.is_valid)
                )

    def _push(self, event) -> None:
        if self.netlink_events_queue is not None:
            self.netlink_events_queue.push(event)

    def stop(self) -> None:  # type: ignore[override]
        if self._sock is not None and self._loop is not None:
            sock = self._sock

            def _close():
                try:
                    self._loop.remove_reader(sock.fileno())
                finally:
                    sock.close()

            try:
                self.run_in_event_base_thread(_close).result(timeout=5)
            except Exception:
                pass
            self._sock = None
        super().stop()
