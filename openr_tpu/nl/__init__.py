"""Netlink library: real rtnetlink codec + AF_NETLINK socket feeding
kernel link/address events into the daemon (reference: openr/nl/ —
NetlinkProtocolSocket, NetlinkMessage codecs)."""

from .netlink import (  # noqa: F401
    AddrInfo,
    LinkInfo,
    NetlinkError,
    NetlinkProtocolSocket,
    parse_messages,
)
