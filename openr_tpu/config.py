"""Config: typed daemon configuration with JSON loading + validation.

Functional equivalent of the reference's Config
(openr/config/Config.{h,cpp} over openr/if/OpenrConfig.thrift:400):
thrift-schema JSON file -> validated typed accessors + per-area config.
Sample: /root/reference/example_openr.conf.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import Any, Optional

from .serializer import register_type
from .spark.spark import AreaConfig, SparkConfig


class ConfigError(ValueError):
    pass


@register_type
@dataclass(slots=True)
class KvStoreConf:
    """Reference: thrift::KvstoreConfig (OpenrConfig.thrift:25)."""

    key_ttl_ms: int = -1
    ttl_decrement_ms: int = 1
    flood_msg_per_sec: int = 0  # 0 == unlimited
    flood_msg_burst_size: int = 0
    key_prefix_filters: list[str] = field(default_factory=list)
    # DUAL flood-topology optimization (reference: enable_flood_optimization
    # / is_flood_root, OpenrConfig.thrift:25 KvstoreConfig)
    enable_flood_optimization: bool = False
    is_flood_root: bool = True


@register_type
@dataclass(slots=True)
class LinkMonitorConf:
    """Reference: thrift::LinkMonitorConfig (OpenrConfig.thrift:74)."""

    linkflap_initial_backoff_ms: int = 1000
    linkflap_max_backoff_ms: int = 60000
    use_rtt_metric: bool = False
    include_interface_regexes: list[str] = field(default_factory=lambda: [".*"])
    exclude_interface_regexes: list[str] = field(default_factory=list)
    redistribute_interface_regexes: list[str] = field(default_factory=list)


@register_type
@dataclass(slots=True)
class DecisionConf:
    debounce_min_ms: int = 10
    debounce_max_ms: int = 250


@register_type
@dataclass(slots=True)
class WatchdogConf:
    """Reference: thrift::WatchdogConfig (OpenrConfig.thrift:145)."""

    interval_s: int = 20
    thread_timeout_s: int = 300
    max_memory_mb: int = 800


@register_type
@dataclass(slots=True)
class PrefixAllocationConf:
    """Reference: thrift::PrefixAllocationConfig (OpenrConfig.thrift:193)."""

    seed_prefix: str = ""
    allocate_prefix_len: int = 128
    # interface to assign the elected prefix's first address to via
    # netlink (reference: PrefixAllocator loopback address sync;
    # set_loopback_address + loopback_interface).  Empty = don't program.
    assign_to_interface: str = ""


@register_type
@dataclass(slots=True)
class SparkConf:
    hello_time_s: float = 20.0
    fastinit_hello_time_ms: float = 500.0
    keepalive_time_s: float = 2.0
    hold_time_s: float = 10.0
    graceful_restart_time_s: float = 30.0


@register_type
@dataclass(slots=True)
class AreaConf:
    area_id: str = "0"
    interface_regexes: list[str] = field(default_factory=lambda: [".*"])
    neighbor_regexes: list[str] = field(default_factory=lambda: [".*"])


@register_type
@dataclass(slots=True)
class TlsConf:
    """mTLS + peer-name ACL on the ctrl transport (reference: wangle TLS
    setup + client-CN allowlist, openr/Main.cpp:546-612; flags
    --x509_cert_path/--x509_key_path/--x509_ca_path/--tls_acl_cache...)."""

    cert_path: str = ""
    key_path: str = ""
    ca_path: str = ""
    acl_regex: str = ".*"  # allowed client certificate CommonNames


@register_type
@dataclass(slots=True)
class OpenrConfig:
    """Reference: thrift::OpenrConfig (OpenrConfig.thrift:400)."""

    node_name: str = ""
    domain: str = "openr"
    areas: list[AreaConf] = field(default_factory=lambda: [AreaConf()])
    listen_addr: str = "::1"
    openr_ctrl_port: int = 2018
    dryrun: bool = False
    enable_v4: bool = True
    enable_segment_routing: bool = True
    enable_best_route_selection: bool = False
    enable_rib_policy: bool = False
    enable_ordered_fib_programming: bool = False
    enable_watchdog: bool = True
    assume_drained: bool = False
    override_drain_state: bool = False
    eor_time_s: Optional[float] = None
    node_label: int = 0
    # thrift Binary+framed interop listener (openr_tpu.interop.shim);
    # 0 disables, -1 binds an ephemeral port (tests)
    thrift_shim_port: int = 0
    persistent_config_store_path: str = ""
    # standalone FibService platform agent endpoint (reference: fib_port
    # gflag, Flags.cpp; 0 == use the in-process mock agent)
    fib_agent_host: str = "::1"
    fib_agent_port: int = 0
    # import path of a plugin module exposing plugin_start(PluginArgs)
    # (reference: the BGP-speaker seam, Plugin.h:23-32 + Main.cpp:501-510)
    plugin_module: str = ""
    # real kernel link/address events via rtnetlink (reference: the nl/
    # NetlinkProtocolSocket producer, Main.cpp:330-343); off by default —
    # tests and mock-fabric deployments inject events directly
    enable_netlink: bool = False
    tls_config: Optional[TlsConf] = None
    kvstore_config: KvStoreConf = field(default_factory=KvStoreConf)
    link_monitor_config: LinkMonitorConf = field(default_factory=LinkMonitorConf)
    decision_config: DecisionConf = field(default_factory=DecisionConf)
    spark_config: SparkConf = field(default_factory=SparkConf)
    watchdog_config: WatchdogConf = field(default_factory=WatchdogConf)
    prefix_allocation_config: Optional[PrefixAllocationConf] = None

    # -- validation (reference: Config::populateInternalDb, Config.h:274) ----

    def validate(self) -> "OpenrConfig":
        if not self.node_name:
            raise ConfigError("node_name is required")
        if not re.fullmatch(r"[a-zA-Z0-9._-]+", self.node_name):
            raise ConfigError(f"invalid node_name {self.node_name!r}")
        if not self.areas:
            raise ConfigError("at least one area is required")
        area_ids = [a.area_id for a in self.areas]
        if len(area_ids) != len(set(area_ids)):
            raise ConfigError("duplicate area ids")
        for area in self.areas:
            for pattern in area.interface_regexes + area.neighbor_regexes:
                try:
                    re.compile(pattern)
                except re.error as e:
                    raise ConfigError(f"bad regex {pattern!r}: {e}") from e
        if self.prefix_allocation_config is not None:
            pac = self.prefix_allocation_config
            if not pac.seed_prefix:
                raise ConfigError("prefix allocation requires seed_prefix")
        if self.tls_config is not None:
            # a present-but-incomplete TLS section must fail loudly — the
            # daemon silently starting PLAINTEXT when the operator set an
            # ACL (or a partial cert set) is a security misconfiguration
            tc = self.tls_config
            if not (tc.cert_path and tc.key_path and tc.ca_path):
                raise ConfigError(
                    "tls_config requires cert_path, key_path and ca_path"
                )
            try:
                re.compile(tc.acl_regex)
            except re.error as e:
                raise ConfigError(
                    f"bad tls acl_regex {tc.acl_regex!r}: {e}"
                ) from e
        if not (0 < self.openr_ctrl_port < 65536) and self.openr_ctrl_port != 0:
            raise ConfigError(f"bad ctrl port {self.openr_ctrl_port}")
        return self

    # -- accessors ------------------------------------------------------------

    @property
    def area_ids(self) -> tuple[str, ...]:
        return tuple(a.area_id for a in self.areas)

    def spark_area_configs(self) -> list[AreaConfig]:
        return [
            AreaConfig(
                area_id=a.area_id,
                interface_regexes=list(a.interface_regexes),
                neighbor_regexes=list(a.neighbor_regexes),
            )
            for a in self.areas
        ]

    def spark_timers(self) -> SparkConfig:
        sc = self.spark_config
        return SparkConfig(
            hello_time_s=sc.hello_time_s,
            fastinit_hello_time_s=sc.fastinit_hello_time_ms / 1000.0,
            keepalive_time_s=sc.keepalive_time_s,
            hold_time_s=sc.hold_time_s,
            graceful_restart_time_s=sc.graceful_restart_time_s,
        )

    def to_dict(self) -> dict[str, Any]:
        from .serializer import _to_jsonable

        return _to_jsonable(self)


def load_config(path: str) -> OpenrConfig:
    """Load + validate a JSON config file (reference: Config(file),
    FATAL on error — we raise ConfigError)."""
    with open(path) as f:
        data = json.load(f)
    return config_from_dict(data)


def config_from_dict(data: dict[str, Any]) -> OpenrConfig:
    from .serializer import _from_jsonable

    cfg = _from_jsonable(OpenrConfig, data)
    return cfg.validate()
