"""Exponential backoff (reference: openr/common/ExponentialBackoff.{h,cpp}:22).

Same semantics as the reference: starts at initial on first error, doubles on
each further error, caps at max; report_success() resets unconditionally;
with is_abort_at_max, a further error while already at max raises (the
reference calls ::abort() there so the supervisor restarts the process —
raising is the in-process equivalent, callers may escalate)."""

from __future__ import annotations

import time


class MaxBackoffAbortError(RuntimeError):
    """Raised on report_error() at max backoff when is_abort_at_max is set."""


class ExponentialBackoff:
    def __init__(
        self,
        initial_backoff_s: float,
        max_backoff_s: float,
        is_abort_at_max: bool = False,
        clock=time.monotonic,
    ) -> None:
        if initial_backoff_s <= 0 or max_backoff_s < initial_backoff_s:
            raise ValueError("invalid backoff bounds")
        self._initial = initial_backoff_s
        self._max = max_backoff_s
        self._is_abort_at_max = is_abort_at_max
        self._clock = clock
        self._current = 0.0
        self._last_error_time = float("-inf")

    def report_success(self) -> None:
        self._last_error_time = float("-inf")
        self._current = 0.0

    def report_error(self) -> None:
        if self._current >= self._max and self._is_abort_at_max:
            raise MaxBackoffAbortError(
                f"max backoff {self._max}s reached with abort-at-max set"
            )
        self._last_error_time = self._clock()
        if self._current == 0.0:
            self._current = self._initial
        else:
            self._current = min(self._current * 2, self._max)

    def can_try_now(self) -> bool:
        return self.get_time_remaining_until_retry() <= 0

    def get_time_remaining_until_retry(self) -> float:
        return max(0.0, (self._last_error_time + self._current) - self._clock())

    def at_max_backoff(self) -> bool:
        return self._current >= self._max

    def get_current_backoff(self) -> float:
        return self._current
