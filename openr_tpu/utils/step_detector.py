"""Step detection over a noisy time series (reference:
openr/common/StepDetector.h:39 — used by Spark to detect significant RTT
changes and emit NEIGHBOR_RTT_CHANGE only on real steps, not jitter).

Two-window mean comparison: the slow window holds the established baseline,
the fast window tracks recent samples.  A step is reported when the fast mean
deviates from the slow mean by more than abs_threshold AND the applicable
percentage threshold; the slow window is then re-seeded from the fast window
so the baseline re-converges at the new level.
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Deque, Optional


class StepDetector:
    def __init__(
        self,
        fast_window_size: int = 10,
        slow_window_size: int = 60,
        lower_threshold_pct: float = 0.4,
        upper_threshold_pct: float = 0.6,
        abs_threshold: float = 500.0,
        on_step: Optional[Callable[[float], None]] = None,
    ) -> None:
        if fast_window_size <= 0 or slow_window_size < fast_window_size:
            raise ValueError("invalid window sizes")
        self._fast: Deque[float] = deque(maxlen=fast_window_size)
        self._slow: Deque[float] = deque(maxlen=slow_window_size)
        self._lower_pct = lower_threshold_pct
        self._upper_pct = upper_threshold_pct
        self._abs = abs_threshold
        self._on_step = on_step

    @property
    def baseline(self) -> Optional[float]:
        if not self._slow:
            return None
        return sum(self._slow) / len(self._slow)

    def add_value(self, sample: float) -> bool:
        """Feed one sample; returns True when a step was detected."""
        self._fast.append(sample)
        if len(self._fast) < self._fast.maxlen or not self._slow:
            # warm-up: seed the slow window once the fast window fills
            self._slow.append(sample)
            return False
        baseline = self.baseline
        fast_mean = sum(self._fast) / len(self._fast)
        diff = abs(fast_mean - baseline)
        pct = diff / baseline if baseline > 0 else float("inf")
        threshold_pct = self._upper_pct if fast_mean > baseline else self._lower_pct
        if diff >= self._abs and pct >= threshold_pct:
            self._slow.clear()
            self._slow.extend(self._fast)
            if self._on_step is not None:
                self._on_step(fast_mean)
            return True
        self._slow.append(sample)
        return False
