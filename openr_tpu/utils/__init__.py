from .backoff import ExponentialBackoff
from .step_detector import StepDetector

__all__ = ["ExponentialBackoff", "StepDetector"]
