"""Synthetic topology builders for tests and benchmarks.

Mirrors the reference benchmark topology generators (grid:
openr/decision/tests/RoutingBenchmarkUtils.h createGrid, fat-tree fabric:
createFabric :320) as AdjacencyDatabase factories for the new framework.
"""

from __future__ import annotations

import random
from typing import Optional

from ..types import Adjacency, AdjacencyDatabase


def _adj(me: str, other: str, metric: int = 1) -> Adjacency:
    return Adjacency(
        other_node_name=other,
        if_name=f"if_{me}_{other}",
        other_if_name=f"if_{other}_{me}",
        metric=metric,
        next_hop_v6=f"fe80::{abs(hash((me, other))) % (1 << 32):x}",
    )


def _bidir(edges: dict[str, list[Adjacency]], a: str, b: str, metric_ab=1, metric_ba=None):
    edges.setdefault(a, []).append(_adj(a, b, metric_ab))
    edges.setdefault(b, []).append(_adj(b, a, metric_ba if metric_ba is not None else metric_ab))


def _to_dbs(edges: dict[str, list[Adjacency]], area: str) -> list[AdjacencyDatabase]:
    return [
        AdjacencyDatabase(
            this_node_name=node,
            adjacencies=adjs,
            area=area,
            node_label=i + 1,
        )
        for i, (node, adjs) in enumerate(sorted(edges.items()))
    ]


def grid_topology(
    n_side: int,
    area: str = "0",
    metric_fn=None,
) -> list[AdjacencyDatabase]:
    """n_side x n_side grid (reference: createGrid in
    RoutingBenchmarkUtils)."""
    edges: dict[str, list[Adjacency]] = {}

    def name(r: int, c: int) -> str:
        return f"node-{r}-{c}"

    for r in range(n_side):
        for c in range(n_side):
            edges.setdefault(name(r, c), [])
            if c + 1 < n_side:
                m = metric_fn(r, c, "h") if metric_fn else 1
                _bidir(edges, name(r, c), name(r, c + 1), m)
            if r + 1 < n_side:
                m = metric_fn(r, c, "v") if metric_fn else 1
                _bidir(edges, name(r, c), name(r + 1, c), m)
    return _to_dbs(edges, area)


def fat_tree_topology(
    n_pods: int,
    n_planes: int = 2,
    n_fsw_per_pod: int = 2,
    n_rsw_per_pod: int = 4,
    n_ssw_per_plane: int | None = None,
    area: str = "0",
) -> list[AdjacencyDatabase]:
    """Three-tier fabric: spine (ssw) planes — fabric (fsw) — rack (rsw)
    (reference: createFabric, RoutingBenchmarkUtils.h:320).  fsw f of a
    pod uplinks to every spine of plane f % n_planes; with the default
    n_ssw_per_plane (== n_fsw_per_pod) this matches the reference's
    square wiring, and an explicit value gives the benchmark fabrics'
    rectangular spine planes."""
    edges: dict[str, list[Adjacency]] = {}
    if n_ssw_per_plane is None:
        n_ssw_per_plane = n_fsw_per_pod
    for plane in range(n_planes):
        for s in range(n_ssw_per_plane):
            edges.setdefault(f"ssw-{plane}-{s}", [])
    for pod in range(n_pods):
        for f in range(n_fsw_per_pod):
            fsw = f"fsw-{pod}-{f}"
            edges.setdefault(fsw, [])
            plane = f % n_planes
            for s in range(n_ssw_per_plane):
                _bidir(edges, fsw, f"ssw-{plane}-{s}")
            for r in range(n_rsw_per_pod):
                _bidir(edges, fsw, f"rsw-{pod}-{r}")
    return _to_dbs(edges, area)


def random_topology(
    n_nodes: int,
    n_extra_edges: int,
    seed: int = 0,
    max_metric: int = 10,
    area: str = "0",
) -> list[AdjacencyDatabase]:
    """Connected random graph: spanning tree + extra edges, random metrics
    (possibly asymmetric per direction)."""
    rng = random.Random(seed)
    names = [f"n{i}" for i in range(n_nodes)]
    edges: dict[str, list[Adjacency]] = {n: [] for n in names}
    seen: set[frozenset] = set()
    for i in range(1, n_nodes):
        j = rng.randrange(i)
        seen.add(frozenset((names[i], names[j])))
        _bidir(
            edges,
            names[i],
            names[j],
            rng.randint(1, max_metric),
            rng.randint(1, max_metric),
        )
    max_extra = n_nodes * (n_nodes - 1) // 2 - (n_nodes - 1)
    n_extra_edges = min(n_extra_edges, max_extra)
    added = 0
    while added < n_extra_edges:
        a, b = rng.sample(names, 2)
        key = frozenset((a, b))
        if key in seen:
            continue
        seen.add(key)
        _bidir(edges, a, b, rng.randint(1, max_metric), rng.randint(1, max_metric))
        added += 1
    return _to_dbs(edges, area)


def ring_topology(n_nodes: int, area: str = "0") -> list[AdjacencyDatabase]:
    edges: dict[str, list[Adjacency]] = {}
    names = [f"r{i}" for i in range(n_nodes)]
    for i in range(n_nodes):
        edges.setdefault(names[i], [])
        if n_nodes > 1 and (i + 1 < n_nodes or n_nodes > 2):
            _bidir(edges, names[i], names[(i + 1) % n_nodes])
    return _to_dbs(edges, area)


def fabric_topology(
    pods: int,
    planes: int = 4,
    ssw_per_plane: int = 4,
    rsw_per_pod: int = 4,
    area: str = "0",
) -> list[AdjacencyDatabase]:
    """Benchmark-shaped fabric (delegates to fat_tree_topology with one
    fsw per plane per pod — the reference's 344/1000/5000-switch
    DecisionBenchmark fabrics scale pods/rsw_per_pod)."""
    return fat_tree_topology(
        pods,
        n_planes=planes,
        n_fsw_per_pod=planes,
        n_rsw_per_pod=rsw_per_pod,
        n_ssw_per_plane=ssw_per_plane,
        area=area,
    )
