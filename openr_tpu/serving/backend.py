"""Batch backends for the QueryScheduler.

Two deployments of the same contract:

- `EngineBatchBackend` — standalone: the scheduler owns `{area:
  LinkState}` views plus a `DeviceSpfBackend`, and dispatches straight
  into the residency engine.  This is the bench/test harness shape and
  the building block for serving tiers that hold their own topology
  mirror.
- `DecisionBatchBackend` — in-daemon: queries marshal onto the Decision
  event thread (the reference's runInEventBaseThread RPC discipline) and
  compute over Decision's own LinkStates through its SpfSolver backend.
  The serving win is unchanged: N coalesced queries cost ONE cross-
  thread marshal and one device dispatch instead of N.

Contract (all methods raise `device.engine.EpochMismatchError` when the
area's topology version no longer matches `expect_epoch`):

- ``epoch(area) -> int`` — current topology version (cheap, lock-free).
- ``run_paths(area, sources, use_link_metric, expect_epoch)`` ->
  ``{source: SpfResult}``.
- ``run_what_if(area, sources, scenarios, expect_epoch)`` -> per-
  scenario impact dicts (protection_api.what_if shape).
- ``run_ksp(area, source, dests, k, expect_epoch)`` ->
  ``{dest: [Path]}``.
- ``run_optimize_metrics(area, demand, bounds, steps, expect_epoch)`` ->
  wire dict of exactly-validated proposed metrics + objective delta (the
  te.TeOptimizer run; epoch-checked per descent step, never retried).

The degradation ladder's host rung lives here: when the engine rejects a
paths dispatch for any non-epoch reason (chaos fault, device loss), the
backend bumps ``serving.host_fallbacks`` and serves the same answer from
the host Dijkstra oracle — overload may shed, but faults keep serving.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional

from ..device.engine import EpochMismatchError

log = logging.getLogger(__name__)


def _noop_bump(name: str, delta: int = 1) -> None:
    return None


def _te_problem_from_csr(csr, demand, bounds):
    """Build a te.TeProblem over a CSR mirror from wire-shaped demand
    triples ((src_name, dest_name, volume), ...).  Edge arrays are COPIED:
    the optimizer runs for many steps on the serving executor while the
    owner thread may refresh the mirror in place — the epoch check aborts
    a moved topology, the copy keeps the in-flight arrays coherent until
    it does.  Unknown node names raise KeyError (a loud error reply)."""
    import numpy as np

    from ..te import TeProblem

    dest_names = sorted({d for (_s, d, _v) in demand})
    if not dest_names:
        raise ValueError("optimize_metrics: empty demand matrix")
    col = {d: j for j, d in enumerate(dest_names)}
    dest_ids = np.array([csr.node_id[d] for d in dest_names], dtype=np.int32)
    dm = np.zeros((csr.node_capacity, len(dest_names)), dtype=np.float32)
    for s, d, v in demand:
        dm[csr.node_id[s], col[d]] += float(v)
    lo, hi = int(bounds[0]), int(bounds[1])
    return TeProblem(
        edge_src=csr.edge_src.copy(),
        edge_dst=csr.edge_dst.copy(),
        edge_metric=csr.edge_metric.copy(),
        edge_up=csr.edge_up.copy(),
        node_overloaded=csr.node_overloaded.copy(),
        n_edges=int(csr.n_edges),
        n_nodes=int(csr.n_nodes),
        dest_ids=dest_ids,
        demand=dm,
        metric_lo=lo,
        metric_hi=hi,
    )


def _shape_te_result(node_names, result) -> dict:
    """TeResult -> wire dict; proposed metrics only for edges the run
    actually changed (and exactly validated), as (src, dest, metric)
    name triples."""
    return {
        "proposedMetrics": [
            [node_names[u], node_names[v], int(m)]
            for (u, v, m) in result.changed_edges
        ],
        "objectiveBefore": float(result.objective_before),
        "objectiveAfter": float(result.objective_after),
        "improved": bool(result.improved),
        "steps": int(result.steps),
        "roundTrips": int(result.round_trips),
        "accepted": int(result.accepted),
        "rejected": int(result.rejected),
    }


class EngineBatchBackend:
    """Standalone backend: {area: LinkState} + DeviceSpfBackend."""

    def __init__(
        self,
        link_states: dict,
        spf_backend=None,
        bump: Optional[Callable[..., None]] = None,
        te=None,
    ) -> None:
        if spf_backend is None:
            from ..decision.spf_solver import DeviceSpfBackend

            spf_backend = DeviceSpfBackend()
        self.link_states = link_states
        self.spf = spf_backend
        self._bump = bump or _noop_bump
        if te is None:
            from ..te import TeOptimizer

            te = TeOptimizer(engine=getattr(spf_backend, "engine", None))
        # TE optimizer rides the same backend so its exact round trips
        # dispatch through the same residency engine; te.* counters are
        # exported by whoever holds this backend (handler te= kwarg)
        self.te = te

    def _ls(self, area: str):
        ls = self.link_states.get(area)
        if ls is None:
            raise KeyError(f"no link state for area {area!r}")
        return ls

    def epoch(self, area: str) -> int:
        return int(self._ls(area).version)

    def _check_epoch(self, ls, expect_epoch: int) -> None:
        if int(ls.version) != int(expect_epoch):
            raise EpochMismatchError(int(expect_epoch), int(ls.version))

    def run_paths(
        self,
        area: str,
        sources: list,
        use_link_metric: bool = True,
        expect_epoch: int = 0,
    ) -> dict:
        ls = self._ls(area)
        self._check_epoch(ls, expect_epoch)
        known = [s for s in sources if ls.links_from_node(s)]
        csr = self.spf.csr_mirror(ls)
        try:
            # engine-level epoch tagging: csr.version mirrors ls.version,
            # so a flap between coalescing and this dispatch raises
            # EpochMismatchError before any device work
            results = self.spf.engine.spf_results(
                csr,
                known,
                use_link_metric=use_link_metric,
                expect_epoch=expect_epoch,
            )
        except EpochMismatchError:
            raise
        except Exception:
            # degradation ladder host rung: the serving layer must keep
            # answering through device faults; same bit-exact contract
            # (to_spf_results is validated against run_spf in tier-1)
            log.debug("serving: engine paths failed; host oracle", exc_info=True)
            self._bump("serving.host_fallbacks")
            self._check_epoch(ls, expect_epoch)
            results = {
                s: ls.get_spf_result(s, use_link_metric=use_link_metric)
                for s in known
            }
        for s in sources:
            if s not in results:
                results[s] = ls.get_spf_result(
                    s, use_link_metric=use_link_metric
                )
        return results

    def run_what_if(
        self,
        area: str,
        sources: list,
        scenarios: list,
        expect_epoch: int = 0,
    ) -> list:
        from ..decision.protection_api import what_if

        ls = self._ls(area)
        self._check_epoch(ls, expect_epoch)
        csr = self.spf.csr_mirror(ls)
        return what_if(
            ls,
            [[tuple(link) for link in sc] for sc in scenarios],
            sources=list(sources) or None,
            csr=csr,
        )

    def run_ksp(
        self,
        area: str,
        source: str,
        dests: list,
        k: int = 2,
        expect_epoch: int = 0,
    ) -> dict:
        ls = self._ls(area)
        self._check_epoch(ls, expect_epoch)
        # one masked device run amortized over the destination set
        self.spf.prefetch_kth_paths(ls, source, list(dests))
        return {d: self.spf.get_kth_paths(ls, source, d, k) for d in dests}

    def run_optimize_metrics(
        self,
        area: str,
        demand,
        bounds,
        steps: int = 32,
        expect_epoch: int = 0,
    ) -> dict:
        ls = self._ls(area)
        self._check_epoch(ls, expect_epoch)
        csr = self.spf.csr_mirror(ls)
        problem = _te_problem_from_csr(csr, demand, bounds)
        result = self.te.optimize(
            problem,
            steps=int(steps),
            # live epoch read: every descent step and exact round trip
            # re-checks; a flap aborts the run (EpochMismatchError), the
            # scheduler does NOT retry this op
            epoch_fn=lambda: int(ls.version),
            expect_epoch=expect_epoch,
        )
        return _shape_te_result(list(csr.node_names), result)


class DecisionBatchBackend:
    """In-daemon backend: batches marshal onto the Decision thread."""

    def __init__(
        self,
        decision,
        bump: Optional[Callable[..., None]] = None,
        te=None,
    ) -> None:
        self.decision = decision
        self._bump = bump or _noop_bump
        if te is None:
            from ..te import TeOptimizer

            te = TeOptimizer(
                engine=getattr(decision.spf_solver.spf, "engine", None)
            )
        self.te = te

    def epoch(self, area: str) -> int:
        # plain read of the version counter: int reads are atomic and the
        # batch re-validates under the Decision thread before computing
        ls = self.decision.area_link_states.get(area)
        return int(ls.version) if ls is not None else -1

    def _ls_checked(self, area: str, expect_epoch: int):
        ls = self.decision.area_link_states.get(area)
        actual = int(ls.version) if ls is not None else -1
        if actual != int(expect_epoch):
            raise EpochMismatchError(int(expect_epoch), actual)
        if ls is None:
            raise KeyError(f"no link state for area {area!r}")
        return ls

    def run_paths(
        self,
        area: str,
        sources: list,
        use_link_metric: bool = True,
        expect_epoch: int = 0,
    ) -> dict:
        def _compute() -> dict:
            ls = self._ls_checked(area, expect_epoch)
            spf = self.decision.spf_solver.spf
            prefetch = getattr(spf, "prefetch", None)
            if prefetch is not None:
                try:
                    # ONE batched device call for the whole source set
                    prefetch(ls, list(sources))
                except EpochMismatchError:
                    raise
                except Exception:
                    log.debug(
                        "serving: decision prefetch failed; host oracle",
                        exc_info=True,
                    )
                    self._bump("serving.host_fallbacks")
            return {
                s: spf.get_spf_result(ls, s)
                for s in sources
                if ls.links_from_node(s)
            }

        return self.decision.run_in_event_base_thread(_compute).result()

    def run_what_if(
        self,
        area: str,
        sources: list,
        scenarios: list,
        expect_epoch: int = 0,
    ) -> list:
        def _check():
            self._ls_checked(area, expect_epoch)

        self.decision.run_in_event_base_thread(_check).result()
        return self.decision.what_if(
            [[tuple(link) for link in sc] for sc in scenarios],
            area=area,
            sources=list(sources) or None,
        )

    def run_ksp(
        self,
        area: str,
        source: str,
        dests: list,
        k: int = 2,
        expect_epoch: int = 0,
    ) -> dict:
        def _compute() -> dict:
            ls = self._ls_checked(area, expect_epoch)
            spf = self.decision.spf_solver.spf
            prefetch = getattr(spf, "prefetch_kth_paths", None)
            if prefetch is not None:
                prefetch(ls, source, list(dests))
            return {d: spf.get_kth_paths(ls, source, d, k) for d in dests}

        return self.decision.run_in_event_base_thread(_compute).result()

    def run_optimize_metrics(
        self,
        area: str,
        demand,
        bounds,
        steps: int = 32,
        expect_epoch: int = 0,
    ) -> dict:
        # only the SNAPSHOT marshals onto the Decision thread (mirror
        # access is single-threaded there); the descent itself runs on
        # the serving executor — a whole optimization must not starve
        # route programming.  The copied problem arrays plus the per-step
        # epoch check keep the off-thread run coherent: a topology event
        # bumps ls.version and the optimizer aborts.
        def _snapshot():
            ls = self._ls_checked(area, expect_epoch)
            spf = self.decision.spf_solver.spf
            mirror = getattr(spf, "csr_mirror", None)
            if mirror is None:
                raise RuntimeError(
                    "optimize_metrics requires the device SPF backend"
                )
            csr = mirror(ls)
            return (
                _te_problem_from_csr(csr, demand, bounds),
                list(csr.node_names),
                ls,
            )

        problem, node_names, ls = self.decision.run_in_event_base_thread(
            _snapshot
        ).result()
        result = self.te.optimize(
            problem,
            steps=int(steps),
            epoch_fn=lambda: int(ls.version),
            expect_epoch=expect_epoch,
        )
        return _shape_te_result(node_names, result)
