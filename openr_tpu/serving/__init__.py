"""Query-serving layer: async admission, batch coalescing, and
double-buffered dispatch in front of the device-residency engine.

The residency engine (openr_tpu.device) is a solver — one caller at a
time through the Decision event loop.  This package turns it into a
service: concurrent clients submit path/what-if/KSP queries into a
bounded admission queue, a coalescer groups compatible queries (same
topology epoch, same op) into one engine dispatch that rides the
existing shape-bucketed program ladder, and a double-buffered dispatch
loop stages batch i+1 while batch i runs.  See
docs/ARCHITECTURE.md "Query-serving layer".
"""

from .backend import DecisionBatchBackend, EngineBatchBackend
from .router import (
    ROUTER_COUNTER_KEYS,
    ReplicaRouter,
    ReplicaUnavailableError,
    SchedulerReplica,
    dispatch_ledger_closes,
)
from .scheduler import (
    SERVING_COUNTER_KEYS,
    Query,
    QueryResult,
    QueryScheduler,
    QueryShedError,
)

__all__ = [
    "DecisionBatchBackend",
    "EngineBatchBackend",
    "Query",
    "QueryResult",
    "QueryScheduler",
    "QueryShedError",
    "ReplicaRouter",
    "ReplicaUnavailableError",
    "dispatch_ledger_closes",
    "ROUTER_COUNTER_KEYS",
    "SchedulerReplica",
    "SERVING_COUNTER_KEYS",
]
