"""ReplicaRouter: one front door over K daemon replicas.

One QueryScheduler saturates one residency engine; scaling past that
means replicas — and the moment there are replicas the hard problem is
robustness, not throughput.  The router owns exactly that problem:

- **Epoch pinning** — every reply carries the LinkState version it was
  answered at (`QueryResult.epoch`).  A session's pin only ever moves
  forward: a reply older than the session's pinned epoch is never
  delivered — the router re-routes the query to a caught-up replica
  (`serving.router.epoch_reroutes`) instead.  This is the DeltaPath
  discipline (PAPERS.md): answers are checkable against the exact
  version they were computed at, so "consistent" is an assertion, not a
  hope.
- **Health + failover** — replica health is tracked from reply outcomes
  plus a liveness probe (an `epoch()` read).  Failures feed a per-replica
  `utils.backoff.ExponentialBackoff`; a dead replica is skipped until its
  backoff window lets a probe try to revive it.  A query whose replica
  died mid-flight is re-dispatched to a survivor
  (`serving.router.failovers`) — never dropped.
- **Bounded hedge** — an unresolved query is speculatively re-dispatched
  to a second replica after `hedge_after_s` (`serving.router.hedges`);
  the first reply wins (`serving.router.hedge_wins` when the hedge beats
  the primary) and the loser's outcome is still observed — it feeds
  replica health and then drops, so duplicate execution is accounted,
  not silent.
- **Loud sheds** — when the router cannot issue even a first dispatch
  (stopped, or no live replica), the caller gets the same explicit
  `QueryShedError` the scheduler's admission queue uses
  (`serving.router.sheds`).  `LoadReport`'s accounted == submitted
  invariant holds over the fleet exactly as it does over one scheduler.

Dispatch ledger (asserted by the chaos family, chaos/replicafleet.py):
every dispatch beyond a query's first is counted in exactly one of
retries / hedges / failovers / epoch_reroutes, and `sheds` counts the
queries that never got a first dispatch, so

    dispatches == (submitted - sheds)
                  + retries + hedges + failovers + epoch_reroutes

reconciles the router's counters against the LoadReport.  (A replica's
*own* admission shed propagates to the caller as QueryShedError after a
bounded retry, but lands in the replica's `serving.shed`, not here.)

The router duck-types `QueryScheduler.submit`/`get_counters`, so the
ctrl handler, the fb303 shim, and `OpenLoopLoadGen` drive a fleet with
no changes — pass `serving=router` instead of `serving=scheduler`.
"""

from __future__ import annotations

import concurrent.futures
import logging
import threading
import time
from typing import Any, Optional

from ..analysis import sched as _sched
from ..device.engine import EpochMismatchError
from ..obs import trace as _trace
from ..obs.histogram import Histogram, export_histogram
from ..utils.backoff import ExponentialBackoff
from .scheduler import QueryResult, QueryShedError

log = logging.getLogger(__name__)

ROUTER_COUNTER_KEYS = (
    "serving.router.dispatches",
    "serving.router.retries",
    "serving.router.hedges",
    "serving.router.hedge_wins",
    "serving.router.failovers",
    "serving.router.epoch_reroutes",
    "serving.router.sheds",
    "serving.router.replica_deaths",
    "serving.router.probe_failures",
)

# replica-scheduler gauges that must not be summed when aggregating the
# fleet's counters onto one wire surface (max is the honest roll-up)
_GAUGE_KEYS = frozenset(
    (
        "serving.batch_occupancy",
        "serving.p50_us",
        "serving.p99_us",
        "serving.p999_us",
        "serving.router.p50_us",
        "serving.router.p99_us",
        "serving.router.p999_us",
    )
)

_HEDGE_TICK_S = 0.005


def dispatch_ledger_closes(counters: dict, submitted: int) -> bool:
    """The router's exactly-closing dispatch identity (module docstring):

        dispatches == (submitted - sheds)
                      + retries + hedges + failovers + epoch_reroutes

    `counters` is a `ReplicaRouter.get_counters()` snapshot taken AFTER
    the router (and its replicas) stopped, so every callback's bumps are
    visible; `submitted` is the caller-side count of queries handed to
    `submit`.  Shared by the chaos replica-fleet scenario and the chaos
    fuzzer's oracle bundle."""
    redispatch = (
        counters["serving.router.retries"]
        + counters["serving.router.hedges"]
        + counters["serving.router.failovers"]
        + counters["serving.router.epoch_reroutes"]
    )
    return counters["serving.router.dispatches"] == (
        submitted - counters["serving.router.sheds"]
    ) + redispatch


class ReplicaUnavailableError(RuntimeError):
    """The replica is down or unreachable (killed process, partition).
    Replica handles raise this (or resolve sub-futures with it) so the
    router can tell a dead replica from an overloaded one."""


class SchedulerReplica:
    """Replica handle over an in-process QueryScheduler.

    The handle protocol the router needs is tiny: `submit(op, **kw)`
    returning a future, `epoch(area)` as the liveness probe, and
    optionally `get_counters()` for the fleet roll-up.  Remote replicas
    implement the same three calls over their wire client.
    """

    def __init__(self, name: str, scheduler) -> None:
        self.name = name
        self.scheduler = scheduler

    def submit(self, op: str, **kw) -> "concurrent.futures.Future":
        return self.scheduler.submit(op, **kw)

    def epoch(self, area: str = "0") -> int:
        return int(self.scheduler.backend.epoch(area))

    def get_counters(self) -> dict:
        return self.scheduler.get_counters()


class _ReplicaState:
    """Router-side view of one replica: handle + health."""

    def __init__(
        self, handle, initial_backoff_s: float, max_backoff_s: float
    ) -> None:
        self.handle = handle
        self.name = str(getattr(handle, "name", repr(handle)))
        self.alive = True
        self.backoff = ExponentialBackoff(initial_backoff_s, max_backoff_s)


class _Call:
    """One caller query's routing state across (re)dispatches."""

    __slots__ = (
        "op",
        "kw",
        "area",
        "session",
        "future",
        "attempts",
        "tried",
        "resolved",
        "hedge_launched",
        "lock",
        "span",
        "t_submit",
    )

    def __init__(self, op: str, kw: dict, area: str, session) -> None:
        self.op = op
        self.kw = kw
        self.area = area
        self.session = session
        self.span = None  # OPENR_TRACE root (None unarmed/sampled out)
        self.t_submit = time.perf_counter()
        self.future: "concurrent.futures.Future[QueryResult]" = (
            concurrent.futures.Future()
        )
        self.attempts = 0
        self.tried: set = set()
        self.resolved = False
        self.hedge_launched = False
        self.lock = threading.Lock()


class ReplicaRouter:
    """Spread queries across K replica schedulers with epoch pinning,
    health-tracked failover, bounded hedging, and loud sheds."""

    def __init__(
        self,
        replicas,
        *,
        hedge_after_s: Optional[float] = 0.05,
        max_attempts: Optional[int] = None,
        initial_backoff_s: float = 0.02,
        max_backoff_s: float = 1.0,
        default_area: str = "0",
    ) -> None:
        self._replicas = [
            _ReplicaState(h, initial_backoff_s, max_backoff_s)
            for h in replicas
        ]
        self._initial_backoff_s = initial_backoff_s
        self._max_backoff_s = max_backoff_s
        self.hedge_after_s = hedge_after_s
        # auto-derived budget tracks the replica count across
        # add_replica/remove_replica; an explicit budget is pinned
        self._auto_max_attempts = max_attempts is None
        self.max_attempts = (
            int(max_attempts)
            if max_attempts is not None
            else max(4, 2 * len(self._replicas))
        )
        # final counter roll-ups of replicas removed by remove_replica:
        # the fleet's wire surface stays monotone across scale-in
        self._departed_counters: dict[str, int] = {}
        self.default_area = default_area
        self._lock = threading.Lock()
        self.counters: dict[str, int] = {k: 0 for k in ROUTER_COUNTER_KEYS}
        # delivered-reply latency (submit -> future resolution), shared
        # log2-bucket histogram -> serving.router.p50/p99/p999_us
        self._hist = Histogram()
        # session -> pinned epoch (monotonically non-decreasing)
        self._sessions: dict[Any, int] = {}
        # test seam: when set to a list, every ACCEPTED (session, epoch)
        # pair is appended under the router lock, in acceptance order —
        # the authoritative record for the monotonicity assertion
        self.pin_trace: Optional[list] = None
        self._rr = 0
        self._stopped = False
        # single monitor thread services every pending hedge deadline
        # (a Timer per query would be a thread per query)
        self._hedge_cv = threading.Condition()
        self._hedge_pending: list = []  # [(deadline, _Call)]
        self._hedge_thread: Optional[threading.Thread] = None
        if self.hedge_after_s and len(self._replicas) > 1:
            self._hedge_thread = threading.Thread(
                target=self._hedge_loop, name="router-hedge", daemon=True
            )
            self._hedge_thread.start()

    # -- counters --------------------------------------------------------------

    def _bump(self, name: str, delta: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + delta

    def get_counters(self) -> dict:
        """Fleet roll-up: summed replica scheduler counters (gauges take
        max) with the router's own `serving.router.*` family on top, so
        one ctrl/fb303 surface exports the whole fleet.  Departed
        replicas' final counters stay folded in (scale-in must never
        make the fleet surface go backwards)."""
        agg: dict[str, int] = {}
        with self._lock:
            agg.update(self._departed_counters)
        for rep in self._replicas:
            fn = getattr(rep.handle, "get_counters", None)
            if fn is None:
                continue
            try:
                c = fn()
            except Exception:  # noqa: BLE001 — a dead replica still rolls up
                continue
            for k, v in c.items():
                if k in _GAUGE_KEYS:
                    agg[k] = max(agg.get(k, 0), int(v))
                else:
                    agg[k] = agg.get(k, 0) + int(v)
        with self._lock:
            agg.update(self.counters)
        export_histogram(agg, "serving.router", self._hist)
        return agg

    # -- health ----------------------------------------------------------------

    def _mark_dead(self, rep: _ReplicaState) -> None:
        with self._lock:
            was_alive = rep.alive
            rep.alive = False
        if was_alive:
            self._bump("serving.router.replica_deaths")

    def _probe(self, rep: _ReplicaState, area: Optional[str] = None) -> bool:
        """Liveness probe: one epoch read.  Success revives, failure
        counts and extends the replica's backoff."""
        try:
            rep.handle.epoch(area or self.default_area)
        except Exception:  # noqa: BLE001 — any probe error means down
            self._bump("serving.router.probe_failures")
            self._mark_dead(rep)
            rep.backoff.report_error()
            return False
        rep.backoff.report_success()
        with self._lock:
            rep.alive = True
        return True

    def probe_replicas(self, area: Optional[str] = None) -> int:
        """Probe every replica; returns how many are alive."""
        return sum(1 for rep in self._replicas if self._probe(rep, area))

    def alive_replicas(self) -> int:
        with self._lock:
            return sum(1 for rep in self._replicas if rep.alive)

    def session_pin(self, session) -> Optional[int]:
        with self._lock:
            return self._sessions.get(session)

    # -- elastic membership (join/leave under live load) -----------------------

    def add_replica(self, handle) -> None:
        """Join a replica under live load.  The membership list is
        swapped atomically under the lock (dispatch paths read it once
        per pick), so in-flight calls keep their ledger accounting and
        the very next pick may route to the newcomer.  Growing past one
        replica starts the hedge monitor if hedging is configured."""
        st = _ReplicaState(
            handle, self._initial_backoff_s, self._max_backoff_s
        )
        with self._lock:
            self._replicas = self._replicas + [st]
            n = len(self._replicas)
            if self._auto_max_attempts:
                self.max_attempts = max(4, 2 * n)
            start_hedge = (
                self.hedge_after_s
                and n > 1
                and self._hedge_thread is None
                and not self._stopped
            )
            if start_hedge:
                self._hedge_thread = threading.Thread(
                    target=self._hedge_loop,
                    name="router-hedge",
                    daemon=True,
                )
        if start_hedge:
            self._hedge_thread.start()

    def remove_replica(self, name: str):
        """Leave under live load: the replica stops receiving new picks
        immediately; its final counters fold into the departed roll-up
        so the fleet surface stays monotone.  Queries already in flight
        on it resolve through _on_reply — a handle its owner stops next
        resolves those futures, which the router re-dispatches as
        failovers — so the dispatch ledger still closes exactly.
        Returns the removed handle (None when unknown)."""
        with self._lock:
            keep = [r for r in self._replicas if r.name != name]
            gone = [r for r in self._replicas if r.name == name]
            if not gone:
                return None
            self._replicas = keep
            if self._auto_max_attempts:
                self.max_attempts = max(4, 2 * max(len(keep), 1))
        rep = gone[0]
        fn = getattr(rep.handle, "get_counters", None)
        final: dict = {}
        if fn is not None:
            try:
                final = fn()
            except Exception:  # noqa: BLE001 — dead at departure is fine
                final = {}
        with self._lock:
            for k, v in final.items():
                if k in _GAUGE_KEYS:
                    self._departed_counters[k] = max(
                        self._departed_counters.get(k, 0), int(v)
                    )
                else:
                    self._departed_counters[k] = (
                        self._departed_counters.get(k, 0) + int(v)
                    )
        return rep.handle

    # -- submission (any thread) -----------------------------------------------

    def submit(
        self,
        op: str,
        *,
        session=None,
        area: str = "0",
        sources=(),
        scenarios=(),
        dests=(),
        k: int = 2,
        use_link_metric: bool = True,
        demand=(),
        bounds=(1, 64),
        steps: int = 32,
    ) -> "concurrent.futures.Future[QueryResult]":
        """QueryScheduler-shaped submit plus optional `session` for epoch
        pinning.  Never blocks; a query the router cannot dispatch at all
        sheds loudly (QueryShedError)."""
        kw = dict(
            area=area,
            sources=sources,
            scenarios=scenarios,
            dests=dests,
            k=k,
            use_link_metric=use_link_metric,
            demand=demand,
            bounds=bounds,
            steps=steps,
        )
        call = _Call(op, kw, area, session)
        tr = _trace.TRACE
        if tr is not None:
            call.span = tr.root("router.query", op=op)
        sc = _sched.SCHED
        if sc is not None:
            # OPENR_SCHED: stop-latch read vs concurrent stop()/replica
            # death — the router's schedule-sensitive dispatch window
            sc.region("router.dispatch")
        if self._stopped or not self._replicas:
            self._resolve_shed(call, "router stopped or no replicas")
            return call.future
        self._dispatch(call, "first")
        return call.future

    # ctrl handler feature probe: pass `session` through the wire params
    supports_sessions = True

    # -- replica selection -----------------------------------------------------

    def _usable(self, rep: _ReplicaState) -> bool:
        if rep.alive:
            return rep.backoff.can_try_now()
        # dead: one probe per expired backoff window may revive it
        if rep.backoff.can_try_now():
            return self._probe(rep)
        return False

    def _pick(
        self,
        call: _Call,
        *,
        require_untried: bool,
        need_epoch: Optional[int],
    ) -> Optional[_ReplicaState]:
        with self._lock:
            start = self._rr
            self._rr += 1
            reps = self._replicas  # one read — membership swaps atomically
        n = len(reps)
        if n == 0:
            return None
        order = [reps[(start + i) % n] for i in range(n)]
        untried = [r for r in order if r.name not in call.tried]
        passes = [untried] if require_untried else [untried, order]
        for candidates in passes:
            behind: list[_ReplicaState] = []
            for rep in candidates:
                if not self._usable(rep):
                    continue
                if need_epoch is not None:
                    try:
                        if int(rep.handle.epoch(call.area)) < need_epoch:
                            behind.append(rep)
                            continue
                    except Exception:  # noqa: BLE001 — probe-style failure
                        self._bump("serving.router.probe_failures")
                        self._mark_dead(rep)
                        rep.backoff.report_error()
                        continue
                return rep
            # no caught-up candidate: a behind-but-alive replica is still
            # better than failing — the stale-reply check re-routes again
            # (bounded by max_attempts) if it answers old
            if behind:
                return behind[0]
        return None

    # -- dispatch --------------------------------------------------------------

    def _dispatch(
        self,
        call: _Call,
        kind: str,
        last_exc: Optional[Exception] = None,
        need_epoch: Optional[int] = None,
    ) -> None:
        """Issue one (re)dispatch of `call`; `kind` names which ledger
        bucket a re-dispatch lands in."""
        while True:
            if self._stopped:
                self._terminal(call, kind, last_exc, "router stopped")
                return
            rep = self._pick(
                call,
                require_untried=(kind == "hedge"),
                need_epoch=need_epoch,
            )
            if rep is None:
                if kind == "hedge":
                    return  # nothing to hedge onto; primary still owns it
                self._terminal(call, kind, last_exc, "no live replica")
                return
            try:
                sp = call.span
                tr = _trace.TRACE if sp is not None else None
                if tr is not None:
                    # the dispatch edge (first/retry/hedge/failover/
                    # epoch_reroute) is structural; activating the call
                    # span makes the replica scheduler's serving.query
                    # span a child of this trace instead of a new root
                    with tr.activate((sp,)):
                        tr.event("dispatch", kind=kind)
                        fut = rep.handle.submit(call.op, **call.kw)
                else:
                    fut = rep.handle.submit(call.op, **call.kw)
            except Exception as e:  # noqa: BLE001 — sync refusal = down
                # no dispatch was issued: not in the ledger, but the
                # replica is marked so the next pick skips it
                self._mark_dead(rep)
                rep.backoff.report_error()
                call.tried.add(rep.name)
                last_exc = e
                continue
            break
        call.tried.add(rep.name)
        with call.lock:
            call.attempts += 1
        if kind == "retry":
            self._bump("serving.router.retries")
        elif kind == "failover":
            self._bump("serving.router.failovers")
        elif kind == "epoch_reroute":
            self._bump("serving.router.epoch_reroutes")
        elif kind == "hedge":
            self._bump("serving.router.hedges")
        self._bump("serving.router.dispatches")
        if kind == "first":
            self._arm_hedge(call)
        hedged = kind == "hedge"
        fut.add_done_callback(
            lambda f, rep=rep, hedged=hedged: self._on_reply(
                call, rep, f, hedged
            )
        )

    def _terminal(
        self,
        call: _Call,
        kind: str,
        last_exc: Optional[Exception],
        why: str,
    ) -> None:
        if kind == "first":
            # never dispatched: the router's own admission shed
            self._resolve_shed(call, f"router shed: {why}")
        else:
            self._resolve_error(
                call,
                last_exc
                or RuntimeError(f"router: re-dispatch impossible ({why})"),
            )

    # -- reply handling (replica executor threads) -----------------------------

    def _on_reply(
        self,
        call: _Call,
        rep: _ReplicaState,
        fut: "concurrent.futures.Future",
        hedged: bool,
    ) -> None:
        try:
            res = fut.result()
        except EpochMismatchError as e:
            # the replica is healthy, its topology just moved between
            # coalesce and dispatch past the scheduler's own retry budget
            self._redispatch(call, "retry", e, hedged)
            return
        except QueryShedError as e:
            # overloaded (or stopping) replica: shed there, retry here
            rep.backoff.report_error()
            self._redispatch(call, "retry", e, hedged)
            return
        except Exception as e:  # noqa: BLE001 — anything else means down
            self._mark_dead(rep)
            rep.backoff.report_error()
            self._redispatch(call, "failover", e, hedged)
            return
        # health first: even a hedge loser's reply is evidence of life
        rep.backoff.report_success()
        need_epoch: Optional[int] = None
        deliver = False
        with self._lock:
            rep.alive = True
            if call.resolved:
                return  # hedge loser: observed, accounted, dropped
            if call.session is not None:
                pin = self._sessions.get(call.session, -1)
                if int(res.epoch) < pin:
                    need_epoch = pin  # stale: re-route, never deliver
                else:
                    self._sessions[call.session] = int(res.epoch)
                    if self.pin_trace is not None:
                        self.pin_trace.append((call.session, int(res.epoch)))
                    call.resolved = True
                    deliver = True
            else:
                call.resolved = True
                deliver = True
        if deliver:
            if hedged:
                self._bump("serving.router.hedge_wins")
            self._hist.record_us(
                int((time.perf_counter() - call.t_submit) * 1e6)
            )
            sp = call.span
            if sp is not None:
                tr = _trace.TRACE
                if tr is not None:
                    sp.tags["outcome"] = "hedge_win" if hedged else "ok"
                    tr.finish_root(sp)
            if not call.future.done():
                call.future.set_result(res)
            return
        self._redispatch(
            call,
            "epoch_reroute",
            EpochMismatchError(need_epoch, int(res.epoch)),
            hedged,
            need_epoch=need_epoch,
        )

    def _redispatch(
        self,
        call: _Call,
        kind: str,
        exc: Exception,
        hedged: bool,
        need_epoch: Optional[int] = None,
    ) -> None:
        with self._lock:
            if call.resolved:
                return
        if hedged and kind != "epoch_reroute":
            # a failed hedge never re-dispatches — the primary chain owns
            # the call; its outcome already fed the replica's health
            return
        with call.lock:
            exhausted = call.attempts >= self.max_attempts
        if exhausted:
            self._resolve_error(call, exc)
            return
        self._dispatch(call, kind, last_exc=exc, need_epoch=need_epoch)

    # -- terminal resolution ---------------------------------------------------

    def _resolve_shed(self, call: _Call, msg: str) -> None:
        with self._lock:
            if call.resolved:
                return
            call.resolved = True
        self._bump("serving.router.sheds")
        self._trace_terminal(call, "shed")
        if not call.future.done():
            call.future.set_exception(QueryShedError(msg))

    def _resolve_error(self, call: _Call, exc: Exception) -> None:
        with self._lock:
            if call.resolved:
                return
            call.resolved = True
        self._trace_terminal(call, "error")
        if not call.future.done():
            call.future.set_exception(exc)

    @staticmethod
    def _trace_terminal(call: _Call, outcome: str) -> None:
        sp = call.span
        if sp is not None:
            tr = _trace.TRACE
            if tr is not None:
                sp.tags["outcome"] = outcome
                tr.finish_root(sp)

    # -- hedging ---------------------------------------------------------------

    def _arm_hedge(self, call: _Call) -> None:
        if self._hedge_thread is None or not self.hedge_after_s:
            return
        deadline = time.monotonic() + self.hedge_after_s
        with self._hedge_cv:
            self._hedge_pending.append((deadline, call))
            self._hedge_cv.notify()

    def _hedge_loop(self) -> None:
        while True:
            with self._hedge_cv:
                if self._stopped:
                    return
                if not self._hedge_pending:
                    self._hedge_cv.wait(timeout=0.2)
                    continue
                now = time.monotonic()
                due = [c for (d, c) in self._hedge_pending if d <= now]
                self._hedge_pending = [
                    (d, c)
                    for (d, c) in self._hedge_pending
                    if d > now and not c.resolved
                ]
            if not due:
                time.sleep(_HEDGE_TICK_S)
                continue
            for call in due:
                with self._lock:
                    if call.resolved or call.hedge_launched:
                        continue
                    call.hedge_launched = True
                self._dispatch(call, "hedge")

    # -- lifecycle -------------------------------------------------------------

    def stop(self) -> None:
        """Stop routing new work.  Replica lifecycles belong to whoever
        built them (main.build_serving_fleet tears the fleet down); the
        replicas' own stop() resolves any in-flight sub-futures, which
        resolves any caller futures still chained through _on_reply."""
        self._stopped = True
        with self._hedge_cv:
            self._hedge_cv.notify_all()
        if self._hedge_thread is not None:
            self._hedge_thread.join(timeout=2.0)
