"""QueryScheduler: admission -> coalesce -> double-buffered dispatch.

Pipeline (one OpenrEventBase thread + one single-worker executor):

1. **Admission** — client threads call `submit()`, which enqueues a
   `_Pending` into a bounded `RWQueue`.  The queue keeps its drop-oldest
   overflow policy, but the serving layer attaches an `on_shed` handler
   so every shed query completes its caller's future with an explicit
   `QueryShedError` — overload sheds loudly, never silently.
2. **Coalescing** — a fiber drains the admission queue and groups
   compatible queries (same op, same area, same topology epoch, same
   mode) into one `_Batch`.  A batch of 5 path queries rides the
   engine's S=8 bucketed program: one dispatch, five replies.
3. **Double-buffered dispatch** — batches move through a 1-slot staging
   queue into a single-worker executor.  While batch i computes on the
   device, the coalescer is already staging batch i+1; when the executor
   frees, the staged batch dispatches immediately.
4. **Invalidation** — each batch pins the topology epoch it coalesced
   against.  The engine (device/engine.py `expect_epoch`) refuses to
   serve a moved topology, so a flap that lands between coalescing and
   dispatch triggers a recompute against the fresh epoch instead of
   serving stale routes.

Accounting lives under `serving.*` and is exported through
`OpenrCtrlHandler._all_counters` / the fb303 shim like every module.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..analysis import sched as _sched
from ..obs import trace as _trace
from ..obs.histogram import Histogram, export_histogram
from ..runtime.eventbase import OpenrEventBase
from ..runtime.queue import QueueClosedError, RWQueue

log = logging.getLogger(__name__)

SERVING_COUNTER_KEYS = (
    "serving.admitted",
    "serving.coalesced",
    "serving.shed",
    "serving.batches",
    "serving.invalidations",
    "serving.host_fallbacks",
    "serving.replies",
    "serving.errors",
    "serving.batch_occupancy",
    "serving.p50_us",
    "serving.p99_us",
    "serving.p999_us",
    "serving.deferrals",
)

# batch-formation hold while topology events are pending (defer_hint):
# per-wait sleep quantum and the bounded total hold per round — queries
# are never deferred past this, storm or not
_DEFER_TICK_S = 0.002
_DEFER_MAX_S = 0.05

# bounded retry against a topology that moves between coalescing and
# dispatch; each retry re-reads the epoch and recomputes fresh
_MAX_EPOCH_RETRIES = 3

_OPS = ("paths", "what_if", "ksp", "optimize_metrics")


class QueryShedError(RuntimeError):
    """The query was shed by admission control (queue overflow, closed
    admission, or scheduler shutdown).  Every shed query gets this as an
    explicit error reply — never a silent drop."""


@dataclass(frozen=True)
class Query:
    """One client question.  `sources`/`dests`/`scenarios` are tuples so
    queries are hashable and batch keys stay value-typed."""

    op: str  # "paths" | "what_if" | "ksp" | "optimize_metrics"
    area: str = "0"
    sources: tuple = ()
    scenarios: tuple = ()  # what_if: tuple of scenario link tuples
    dests: tuple = ()  # ksp
    k: int = 2  # ksp
    use_link_metric: bool = True  # paths
    demand: tuple = ()  # optimize_metrics: ((src, dest, volume), ...)
    bounds: tuple = (1, 64)  # optimize_metrics: (metric_lo, metric_hi)
    steps: int = 32  # optimize_metrics: descent steps


@dataclass
class QueryResult:
    """Per-query reply with latency attribution."""

    value: Any
    latency_us: int
    batch_size: int
    epoch: int


@dataclass(eq=False)  # identity semantics: lives in the _inflight set
class _Pending:
    query: Query
    future: "concurrent.futures.Future[QueryResult]"
    t_submit: float
    # OPENR_TRACE only: the query's root span and the stage-boundary
    # timestamps the reply path turns into admission/coalesce children.
    span: Any = None
    t_drain: float = 0.0
    t_stage: float = 0.0


@dataclass
class _Batch:
    key: tuple
    op: str
    area: str
    epoch: int
    pendings: list = field(default_factory=list)


class QueryScheduler(OpenrEventBase):
    """Serving front-end between the ctrl/thrift surfaces and a batch
    backend (serving.backend): admission queue, epoch-keyed coalescer,
    double-buffered dispatch loop."""

    def __init__(
        self,
        backend,
        max_pending: int = 1024,
        max_coalesce: int = 64,
        defer_hint: Optional[Callable[[], int]] = None,
    ) -> None:
        super().__init__(name="serving")
        self.backend = backend
        # event-batching composition with the decision delta rung: a
        # non-zero hint (Decision.pending_event_hint — topology events
        # admitted but not yet folded into routes) holds batch formation
        # for a BOUNDED beat so the batch pins the post-storm epoch and
        # rides the delta-updated product instead of racing an epoch
        # about to be invalidated.  None keeps the legacy behavior.
        self.defer_hint = defer_hint
        # route the backend's counter bumps (serving.host_fallbacks) into
        # this scheduler's serving.* registry
        if hasattr(backend, "_bump"):
            backend._bump = self._bump
        self.max_coalesce = max_coalesce
        # bounded admission: overflow sheds the OLDEST pending query and
        # the on_shed hook turns that into an explicit error reply
        self.admission: RWQueue[_Pending] = RWQueue(
            maxlen=max_pending, on_shed=self._on_admission_shed
        )
        self._accepting = True
        # 1-slot staging queue + 1-worker executor = the double buffer:
        # the coalescer fills the slot while the worker runs batch i
        self._staged: Optional[asyncio.Queue] = None
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="serving-exec"
        )
        self._lock = threading.Lock()
        self.counters: dict[str, int] = {k: 0 for k in SERVING_COUNTER_KEYS}
        # shared log2-bucket histogram: O(1) record, O(buckets) read —
        # replaces the sorted(deque)-per-get_counters percentile snapshot
        self._hist = Histogram()
        self._occupancy_sum = 0
        self._occupancy_batches = 0
        # every admitted-but-unanswered query; anything left here at
        # shutdown is failed explicitly (zero silent drops)
        self._inflight: set = set()
        # test/chaos seam: called with (event, batch) at stage and
        # execute boundaries — the double-buffer overlap test hangs here
        self.trace_hook: Optional[Callable[[str, Any], None]] = None

    # -- counters ------------------------------------------------------------

    def _bump(self, name: str, delta: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + delta

    def get_counters(self) -> dict[str, int]:
        with self._lock:
            counters = dict(self.counters)
            occ_sum = self._occupancy_sum
            occ_n = self._occupancy_batches
        # derived gauges: mean batch occupancy in milli-queries-per-batch
        # (integer wire format), latency percentiles from the histogram
        counters["serving.batch_occupancy"] = (
            (occ_sum * 1000) // occ_n if occ_n else 0
        )
        export_histogram(counters, "serving", self._hist)
        return counters

    # -- admission (any thread) ----------------------------------------------

    def submit(
        self,
        op: str,
        *,
        area: str = "0",
        sources=(),
        scenarios=(),
        dests=(),
        k: int = 2,
        use_link_metric: bool = True,
        demand=(),
        bounds=(1, 64),
        steps: int = 32,
    ) -> "concurrent.futures.Future[QueryResult]":
        """Enqueue one query; returns a future resolving to QueryResult
        or raising QueryShedError / the compute error.  Never blocks the
        caller: over capacity, admission sheds (explicitly)."""
        if op not in _OPS:
            raise ValueError(f"unknown op {op!r} (expected one of {_OPS})")
        query = Query(
            op=op,
            area=area,
            sources=tuple(sources),
            scenarios=tuple(tuple(tuple(l) for l in sc) for sc in scenarios),
            dests=tuple(dests),
            k=int(k),
            use_link_metric=bool(use_link_metric),
            demand=tuple(
                (str(s), str(d), float(v)) for (s, d, v) in demand
            ),
            bounds=(int(bounds[0]), int(bounds[1])),
            steps=int(steps),
        )
        fut: "concurrent.futures.Future[QueryResult]" = (
            concurrent.futures.Future()
        )
        pending = _Pending(query, fut, time.perf_counter())
        tr = _trace.TRACE
        if tr is not None:
            # trace-context birth: extends the router's span when one is
            # active on this thread, else starts (and samples) a new root
            pending.span = tr.root("serving.query", op=op)
        sc = _sched.SCHED
        if sc is not None:
            # OPENR_SCHED: the accepting-latch read vs stop() is the
            # scheduler's schedule-sensitive window (sched_shutdown_vs_future)
            sc.region("serving.admission")
        if not self._accepting or not self.admission.push(pending):
            # _fail, not a bare set_exception: it also closes the trace
            # span (outcome=shed) that was opened above
            self._fail(pending, QueryShedError("admission closed"))
            return fut
        with self._lock:
            self._inflight.add(pending)
        self._bump("serving.admitted")
        return fut

    def _on_admission_shed(self, pending: _Pending) -> None:
        # runs on the pushing thread, OUTSIDE the queue lock
        self._fail(pending, QueryShedError("admission queue overflow"))

    def _fail(self, pending: _Pending, exc: Exception) -> None:
        with self._lock:
            self._inflight.discard(pending)
        if pending.future.done():
            return
        if isinstance(exc, QueryShedError):
            self._bump("serving.shed")
        else:
            self._bump("serving.errors")
        sp = pending.span
        if sp is not None:
            tr = _trace.TRACE
            if tr is not None:
                sp.tags["outcome"] = (
                    "shed" if isinstance(exc, QueryShedError) else "error"
                )
                tr.finish_root(sp)
        pending.future.set_exception(exc)

    # -- coalescing (event-base fiber) ---------------------------------------

    @staticmethod
    def _batch_key(query: Query, epoch: int) -> tuple:
        if query.op == "paths":
            return ("paths", query.area, epoch, query.use_link_metric)
        if query.op == "what_if":
            # what-if impact counting is relative to the source set, so
            # only identical views coalesce (scenarios concatenate)
            return ("what_if", query.area, epoch, query.sources)
        if query.op == "optimize_metrics":
            # only IDENTICAL optimization requests coalesce (same demand
            # matrix, bounds, budget): they share one descent run and one
            # answer; anything else is its own batch
            return (
                "optimize_metrics", query.area, epoch, query.demand,
                query.bounds, query.steps,
            )
        return ("ksp", query.area, epoch, query.sources, query.k)

    async def prepare(self) -> None:
        self._staged = asyncio.Queue(maxsize=1)
        loop = asyncio.get_running_loop()
        self._track(
            loop.create_task(self._coalesce_loop(), name="serving-coalesce")
        )
        self._track(
            loop.create_task(self._dispatch_loop(), name="serving-dispatch")
        )

    async def _coalesce_loop(self) -> None:
        try:
            while True:
                first = await self.admission.aget()
                drained = [first]
                while len(drained) < self.max_coalesce:
                    try:
                        nxt = self.admission.try_get()
                    except QueueClosedError:
                        break
                    if nxt is None:
                        break
                    drained.append(nxt)
                if _trace.TRACE is not None:
                    t_drain = time.perf_counter()
                    for pending in drained:
                        if pending.span is not None:
                            pending.t_drain = t_drain
                # defer-on-pending-events: hold the round (bounded) while
                # the decision layer still has unfolded topology events,
                # so the epoch pinned below is the post-coalesce one —
                # without this a storm turns into pin/dispatch/invalidate
                # churn through the epoch-retry loop instead of one clean
                # batch against the delta-updated product
                if self.defer_hint is not None:
                    deadline = time.perf_counter() + _DEFER_MAX_S
                    deferred = False
                    while (
                        self.defer_hint() > 0
                        and time.perf_counter() < deadline
                    ):
                        deferred = True
                        await asyncio.sleep(_DEFER_TICK_S)
                    if deferred:
                        self._bump("serving.deferrals")
                # one epoch read per area per round: every query grouped
                # here pins the SAME topology version
                epochs: dict[str, int] = {}
                batches: dict[tuple, _Batch] = {}
                for pending in drained:
                    q = pending.query
                    epoch = epochs.get(q.area)
                    if epoch is None:
                        epoch = int(self.backend.epoch(q.area))
                        epochs[q.area] = epoch
                    key = self._batch_key(q, epoch)
                    batch = batches.get(key)
                    if batch is None:
                        batch = _Batch(key, q.op, q.area, epoch)
                        batches[key] = batch
                    batch.pendings.append(pending)
                for batch in batches.values():
                    if self.trace_hook is not None:
                        self.trace_hook("stage", batch)
                    if _trace.TRACE is not None:
                        t_stage = time.perf_counter()
                        for pending in batch.pendings:
                            if pending.span is not None:
                                pending.t_stage = t_stage
                    await self._staged.put(batch)
        except (QueueClosedError, asyncio.CancelledError):
            pass

    # -- dispatch (double buffer) --------------------------------------------

    async def _dispatch_loop(self) -> None:
        loop = asyncio.get_running_loop()
        try:
            while True:
                batch = await self._staged.get()
                # hand the batch to the single worker; the staging slot is
                # now free, so the coalescer overlaps batch i+1 with this
                # execution
                await loop.run_in_executor(self._pool, self._execute, batch)
        except asyncio.CancelledError:
            pass

    def _execute(self, batch: _Batch) -> None:
        from ..device.engine import EpochMismatchError

        if self.trace_hook is not None:
            self.trace_hook("execute_begin", batch)
        try:
            per_query: Optional[list] = None
            error: Optional[Exception] = None
            # optimize_metrics never retries an epoch mismatch: a flap
            # mid-descent means the whole run optimized a topology that
            # no longer exists — the run aborts loudly (the caller sees
            # EpochMismatchError) instead of silently re-pinning and
            # publishing metrics tuned for the stale graph
            attempts = (
                1 if batch.op == "optimize_metrics" else _MAX_EPOCH_RETRIES
            )
            tr = _trace.TRACE
            d_spans: list = []
            if tr is not None:
                # one open "dispatch" child per traced query in the batch;
                # activating them all lets ONE engine-rung annotation land
                # on every coalesced query's tree (fan-in scope)
                d_spans = [
                    tr.child_open(p.span, "dispatch")
                    for p in batch.pendings
                    if p.span is not None
                ]
            for _attempt in range(attempts):
                try:
                    if d_spans:
                        with tr.activate(d_spans):
                            per_query = self._run_batch(batch)
                    else:
                        per_query = self._run_batch(batch)
                    error = None
                    break
                except EpochMismatchError as e:
                    # a flap landed between coalescing and dispatch:
                    # re-pin the fresh epoch and recompute — coalesced
                    # work is invalidated, never served stale
                    self._bump("serving.invalidations")
                    if d_spans:
                        with tr.activate(d_spans):
                            tr.event("epoch_retry")
                    batch.epoch = int(self.backend.epoch(batch.area))
                    error = e
                except Exception as e:  # noqa: BLE001
                    log.debug(
                        "serving: batch %s failed", batch.op, exc_info=True
                    )
                    error = e
                    break
            if d_spans:
                for ds in d_spans:
                    ds.finish()
            n = len(batch.pendings)
            with self._lock:
                self.counters["serving.batches"] += 1
                self._occupancy_sum += n
                self._occupancy_batches += 1
            if n > 1:
                self._bump("serving.coalesced", n - 1)
            if error is not None or per_query is None:
                exc = error or RuntimeError("serving: batch produced nothing")
                for pending in batch.pendings:
                    self._fail(pending, exc)
                return
            t_done = time.perf_counter()
            for pending, value in zip(batch.pendings, per_query):
                latency_us = int((t_done - pending.t_submit) * 1e6)
                with self._lock:
                    self._inflight.discard(pending)
                self._hist.record_us(latency_us)
                sp = pending.span
                if sp is not None and tr is not None:
                    self._trace_reply(tr, pending, t_done)
                if pending.future.done():
                    continue
                self._bump("serving.replies")
                pending.future.set_result(
                    QueryResult(
                        value=value,
                        latency_us=latency_us,
                        batch_size=n,
                        epoch=batch.epoch,
                    )
                )
        finally:
            if self.trace_hook is not None:
                self.trace_hook("execute_end", batch)

    @staticmethod
    def _trace_reply(tr, pending: _Pending, t_done: float) -> None:
        """Turn the recorded stage boundaries into completed children and
        close out the query's trace: admission -> coalesce -> dispatch ->
        reply (the dispatch child was opened live in _execute so engine
        rung annotations landed on it)."""
        sp = pending.span

        def us(t: float) -> int:
            return int(t * 1e6)  # same clock as Span (perf_counter)

        if pending.t_drain:
            tr.stage(sp, "admission", us(pending.t_submit), us(pending.t_drain))
            if pending.t_stage:
                tr.stage(sp, "coalesce", us(pending.t_drain), us(pending.t_stage))
        tr.stage(sp, "reply", us(t_done), us(t_done))
        sp.tags["outcome"] = "ok"
        tr.finish_root(sp)

    def _run_batch(self, batch: _Batch) -> list:
        """One backend call for the whole batch; returns per-query values
        aligned with batch.pendings."""
        queries = [p.query for p in batch.pendings]
        if batch.op == "optimize_metrics":
            # the batch key made every member identical: ONE descent run
            # (epoch-checked per step by the optimizer) answers them all
            q = queries[0]
            result = self.backend.run_optimize_metrics(
                batch.area,
                q.demand,
                q.bounds,
                steps=q.steps,
                expect_epoch=batch.epoch,
            )
            return [result for _ in queries]
        if batch.op == "paths":
            # stable-order union of every query's sources
            merged = list(
                dict.fromkeys(s for q in queries for s in q.sources)
            )
            results = self.backend.run_paths(
                batch.area,
                merged,
                use_link_metric=queries[0].use_link_metric,
                expect_epoch=batch.epoch,
            )
            return [
                {s: results[s] for s in q.sources if s in results}
                for q in queries
            ]
        if batch.op == "what_if":
            merged_sc: list = []
            offsets: list[tuple[int, int]] = []
            for q in queries:
                offsets.append(
                    (len(merged_sc), len(merged_sc) + len(q.scenarios))
                )
                merged_sc.extend(list(map(list, sc)) for sc in q.scenarios)
            rows = self.backend.run_what_if(
                batch.area,
                list(queries[0].sources),
                merged_sc,
                expect_epoch=batch.epoch,
            )
            out = []
            for lo, hi in offsets:
                mine = []
                for i, row in enumerate(rows[lo:hi]):
                    row = dict(row)
                    row["scenario"] = i  # renumber to the query's view
                    mine.append(row)
                out.append(mine)
            return out
        # ksp: one source, union of destination sets
        merged_d = list(dict.fromkeys(d for q in queries for d in q.dests))
        source = queries[0].sources[0] if queries[0].sources else ""
        results = self.backend.run_ksp(
            batch.area,
            source,
            merged_d,
            k=queries[0].k,
            expect_epoch=batch.epoch,
        )
        return [{d: results.get(d, []) for d in q.dests} for q in queries]

    # -- shutdown ------------------------------------------------------------

    async def stopping(self) -> None:
        self._accepting = False
        self.admission.close()
        # fail everything still waiting in admission
        while True:
            try:
                pending = self.admission.try_get()
            except QueueClosedError:
                break
            if pending is None:
                break
            self._fail(pending, QueryShedError("scheduler stopping"))
        # and a staged-but-undispatched batch
        if self._staged is not None:
            while not self._staged.empty():
                batch = self._staged.get_nowait()
                for pending in batch.pendings:
                    self._fail(pending, QueryShedError("scheduler stopping"))

    def stop(self) -> None:
        self._accepting = False
        super().stop()
        # let an in-flight batch finish answering its callers, then fail
        # any stragglers: every admitted query resolves, one way or the
        # other
        self._pool.shutdown(wait=True)
        with self._lock:
            leftovers = [p for p in self._inflight if not p.future.done()]
        for pending in leftovers:
            self._fail(pending, QueryShedError("scheduler stopped"))
