"""Multi-chip scale-out for the SPF compute plane.

The reference replicates the whole computation on every router (SURVEY §2.2;
openr/decision/LinkState.cpp:809 — each node runs its own Dijkstras).  The
TPU build instead *shards* the batched SSSP over a `jax.sharding.Mesh`:

- the source-batch dimension S (independent SPF problems: sources ×
  metric variants × what-if exclusion masks) shards over the `"batch"`
  mesh axis — embarrassingly parallel, zero collectives;
- the node dimension N of the distance tensor shards over the `"node"`
  mesh axis for topologies whose [S, N] state exceeds one chip's HBM —
  the per-iteration gather over `edge_src` then rides ICI all-gathers
  inserted by XLA.

This module is transport-free: it only places arrays.  Host-to-host state
replication (the KvStore mesh) is a separate subsystem.
"""

from .blocked import BlockedApspEngine, make_blocked_mesh
from .mesh import (
    make_mesh,
    sharded_spf_forward,
    spf_step_sharded,
)

__all__ = [
    "BlockedApspEngine",
    "make_blocked_mesh",
    "make_mesh",
    "sharded_spf_forward",
    "spf_step_sharded",
]
