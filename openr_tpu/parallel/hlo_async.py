"""Async-collective evidence for the pipelined blocked closure.

`parallel.blocked.blocked_round_pipelined` fuses round k's rank-B
outer update with round k+1's panel prefetch so the prefetch
collectives carry no data dependence on the outer-update while loop.
On TPU, XLA's AsyncCollectiveCreator + latency-hiding scheduler turn
that independence into `all-gather-start`/`all-gather-done` pairs that
bracket the compute.  The CPU backend never emits the async pair (its
thunk runtime overlaps independent thunks as a dataflow DAG instead),
so "the pairs span the outer update" cannot be grepped out of a CPU
module directly — it has to be PROVED from the module.

This module does exactly that, from the lowered scheduled HLO text and
nothing else:

  * parse the ENTRY computation of a compiled (`is_scheduled=true`)
    module into its instruction list + def-use graph;
  * for every `all-gather`, split it into a start/done pair and
    re-list-schedule the entry with the same legality rule XLA's async
    scheduler uses — an op may sit between start and done iff it is
    neither a transitive producer of the gather's operands nor a
    transitive consumer of its result (checked per span, not assumed);
  * emit the materialized schedule as HLO-shaped text plus a span
    report: which compute ops each start/done pair brackets, whether
    the rank-5 outer-update while is inside, and the collective bytes.

The materialized text is evidence, not an executable: it is the
schedule the async pass is entitled to produce, derived from the real
def-use chains of the real compiled module — "verified from lowered
HLO, not hoped for".
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

#: dtype byte widths for the shapes that appear in the blocked closure
_DTYPE_BYTES = {
    "pred": 1,
    "s8": 1,
    "u8": 1,
    "s16": 2,
    "u16": 2,
    "bf16": 2,
    "f16": 2,
    "s32": 4,
    "u32": 4,
    "f32": 4,
    "s64": 8,
    "u64": 8,
    "f64": 8,
}

_INSTR_RE = re.compile(r"^\s*(?P<root>ROOT\s+)?%(?P<name>[\w.-]+)\s*=\s*(?P<rhs>.*)$")
_OPCODE_RE = re.compile(r"^([a-z][\w-]*)\(")
#: rank-5 u32 per-shard array — the blocked outer update's carry type;
#: no other while in the fused round carries a 5-D operand
_RANK5_U32_RE = re.compile(r"u32\[\d+,\d+,\d+,\d+,\d+\]")


@dataclass
class Instr:
    """One scheduled ENTRY instruction (schedule order == line order
    in a compiled module)."""

    index: int
    name: str
    shape: str
    opcode: str
    operands: list[str]
    attrs: str
    is_root: bool = False
    deps: list[str] = field(default_factory=list)  # operands defined in entry


def _split_shape(rhs: str) -> tuple[str, str]:
    """Split `rhs` into (shape, rest).  Tuple shapes are parenthesized
    and contain no nested parens; array shapes are a single token."""
    if rhs.startswith("("):
        end = rhs.index(")")
        return rhs[: end + 1], rhs[end + 1 :].lstrip()
    parts = rhs.split(" ", 1)
    return parts[0], parts[1] if len(parts) > 1 else ""


def _balanced_args(rest: str, start: int) -> tuple[str, str]:
    """Return (args, attrs) for the operand list opening at
    rest[start] == '('.  Operand lists nest parens only through tuple
    shape annotations, so a depth counter suffices."""
    depth = 0
    for i in range(start, len(rest)):
        if rest[i] == "(":
            depth += 1
        elif rest[i] == ")":
            depth -= 1
            if depth == 0:
                return rest[start + 1 : i], rest[i + 1 :].lstrip(", ")
    raise ValueError(f"unbalanced operand list in HLO line: {rest!r}")


def shape_bytes(shape: str) -> int:
    """Total bytes of an array (or tuple) shape string, layouts
    ignored; scalar shapes like `u32[]` count one element."""
    total = 0
    for dtype, dims in re.findall(r"(\w+)\[([\d,]*)\]", shape):
        width = _DTYPE_BYTES.get(dtype, 4)
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * width
    return total


def parse_entry(text: str) -> list[Instr]:
    """Parse the ENTRY computation of a compiled scheduled module into
    schedule-ordered instructions with entry-local def-use edges."""
    header = text.split("\n", 1)[0]
    if "is_scheduled=true" not in header:
        raise ValueError(
            "hlo_async needs a COMPILED module (is_scheduled=true): the "
            "instruction order of an unscheduled module is not a schedule"
        )
    lines = text.splitlines()
    try:
        first = next(i for i, l in enumerate(lines) if l.startswith("ENTRY "))
    except StopIteration:
        raise ValueError("no ENTRY computation in HLO module") from None
    instrs: list[Instr] = []
    for line in lines[first + 1 :]:
        if line.startswith("}"):
            break
        m = _INSTR_RE.match(line)
        if not m:
            continue
        shape, rest = _split_shape(m.group("rhs"))
        op = _OPCODE_RE.match(rest)
        if not op:
            continue
        args, attrs = _balanced_args(rest, op.end() - 1)
        operands = re.findall(r"%([\w.-]+)", args)
        instrs.append(
            Instr(
                index=len(instrs),
                name=m.group("name"),
                shape=shape,
                opcode=op.group(1),
                operands=operands,
                attrs=attrs,
                is_root=bool(m.group("root")),
            )
        )
    known = {i.name for i in instrs}
    for i in instrs:
        i.deps = [o for o in i.operands if o in known]
    return instrs


def _closure(edges: dict[str, list[str]], seeds: list[str]) -> set[str]:
    out: set[str] = set()
    stack = list(seeds)
    while stack:
        n = stack.pop()
        if n in out:
            continue
        out.add(n)
        stack.extend(edges.get(n, ()))
    return out


def find_outer_update(instrs: list[Instr]) -> str | None:
    """The round-k outer update: the only while whose carry holds the
    rank-5 u32 tile tensor."""
    for i in instrs:
        if i.opcode == "while" and _RANK5_U32_RE.search(i.shape):
            return i.name
    return None


def materialize(text: str) -> tuple[str, list[dict]]:
    """Split every entry `all-gather` into an `all-gather-start` /
    `all-gather-done` pair and re-list-schedule the entry so each done
    sinks to the last legal point (just before its first consumer,
    after every ready independent op).  Returns (materialized entry
    text, span report).

    Legality is the async scheduler's rule, checked per span from the
    parsed def-use graph: an op between start and done must be neither
    a transitive producer of the gather's operands nor a transitive
    consumer of its result.  The list schedule is a topological order
    by construction, and dones are emitted only when every remaining
    node depends on one — i.e. every gather-independent op (including
    the outer-update while) lands inside every open span."""
    instrs = parse_entry(text)
    by_name = {i.name: i for i in instrs}
    gathers = [i for i in instrs if i.opcode == "all-gather"]

    # node graph with each gather split into start (the gather's deps)
    # and done (the start); users of the gather now consume the done,
    # which keeps every other instruction line textually unchanged
    deps: dict[str, list[str]] = {}
    prio: dict[str, tuple[int, int]] = {}
    done_names = {g.name for g in gathers}
    for i in instrs:
        if i.name in done_names:
            deps[i.name + "-start"] = list(i.deps)
            prio[i.name + "-start"] = (i.index, 0)
            deps[i.name] = [i.name + "-start"]
            prio[i.name] = (i.index, 1)
        else:
            deps[i.name] = list(i.deps)
            prio[i.name] = (i.index, 0)

    emitted: set[str] = set()
    order: list[str] = []
    remaining = set(deps)
    while remaining:
        ready = [n for n in remaining if all(d in emitted for d in deps[n])]
        if not ready:
            raise ValueError("cycle in HLO entry def-use graph")
        non_done = [n for n in ready if n not in done_names]
        pick = min(non_done or ready, key=lambda n: prio[n])
        order.append(pick)
        emitted.add(pick)
        remaining.remove(pick)

    # emit text
    users: dict[str, list[str]] = {}
    for i in instrs:
        for d in i.deps:
            users.setdefault(d, []).append(i.name)

    def render(name: str) -> str:
        if name.endswith("-start") and name[:-6] in done_names:
            g = by_name[name[:-6]]
            op_shapes = ", ".join(by_name[o].shape for o in g.deps) or g.shape
            attrs = f", {g.attrs}" if g.attrs else ""
            args = ", ".join(f"{by_name[o].shape} %{o}" for o in g.deps)
            return (
                f"  %{g.name}-start = ({op_shapes}, {g.shape}) "
                f"all-gather-start({args}){attrs}"
            )
        i = by_name[name]
        if name in done_names:
            return (
                f"  %{i.name} = {i.shape} all-gather-done("
                f"(..., {i.shape}) %{i.name}-start)"
            )
        root = "ROOT " if i.is_root else ""
        args = ", ".join(
            f"{by_name[o].shape} %{o}" if o in by_name else f"%{o}"
            for o in i.operands
        )
        attrs = f", {i.attrs}" if i.attrs else ""
        return f"  {root}%{i.name} = {i.shape} {i.opcode}({args}){attrs}"

    pos = {n: k for k, n in enumerate(order)}
    spans: list[dict] = []
    outer = find_outer_update(instrs)
    for g in gathers:
        lo, hi = pos[g.name + "-start"], pos[g.name]
        inside = [n for n in order[lo + 1 : hi] if not n.endswith("-start")]
        # per-span legality check from the def-use graph — not assumed
        # from the scheduler's construction
        producers = _closure(
            {i.name: i.deps for i in instrs}, list(g.deps)
        )
        consumers = _closure(users, users.get(g.name, []))
        illegal = [n for n in inside if n in producers or n in consumers]
        compute = [
            n
            for n in inside
            if by_name.get(n) and by_name[n].opcode in ("while", "fusion")
        ]
        spans.append(
            {
                "name": g.name,
                "start": lo,
                "done": hi,
                "ops_in_span": inside,
                "compute_in_span": compute,
                "spans_outer_update": outer is not None and outer in inside,
                "legal": not illegal,
                "illegal_ops": illegal,
                "bytes_out": shape_bytes(g.shape),
                "bytes_in": sum(shape_bytes(by_name[o].shape) for o in g.deps),
            }
        )

    body = "\n".join(render(n) for n in order)
    return f"ENTRY %async_materialized {{\n{body}\n}}\n", spans


def async_report(text: str) -> dict:
    """Analyze a compiled pipelined-round module: materialize the async
    spans and summarize the overlap evidence.

    Returns a dict with `spans` (per-gather report from
    `materialize`), `outer_update` (the rank-5 while's name or None),
    `outer_spanning` (how many legal spans bracket the outer update —
    the two PANEL gathers must; the diagonal replication is dep-chained
    through the row-panel gather, so a linear schedule provably cannot
    put the while inside all three), `panel_overlap_ok`
    (outer_spanning >= 2), `collective_bytes` (sum of gathered output
    bytes), and `overlap_frac_est` (percent of entry compute ops —
    whiles and fusions — scheduled inside at least one span)."""
    instrs = parse_entry(text)
    materialized, spans = materialize(text)
    covered: set[str] = set()
    for s in spans:
        covered.update(s["compute_in_span"])
    compute = [i.name for i in instrs if i.opcode in ("while", "fusion")]
    frac = 100 * len([c for c in compute if c in covered]) // max(len(compute), 1)
    outer_spanning = len(
        [s for s in spans if s["spans_outer_update"] and s["legal"]]
    )
    return {
        "spans": spans,
        "outer_update": find_outer_update(instrs),
        "outer_spanning": outer_spanning,
        "panel_overlap_ok": outer_spanning >= 2,
        "collective_bytes": sum(s["bytes_out"] for s in spans),
        "overlap_frac_est": frac,
        "n_collectives": len(spans),
        "materialized": materialized,
    }
