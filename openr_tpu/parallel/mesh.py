"""Mesh-sharded batched SSSP.

Sharding layout (scaling-book style: pick a mesh, annotate shardings, let
XLA insert the collectives):

    mesh axes:          ("batch", "node")
    sources [S]         P("batch")
    dist    [S, N]      P("batch", "node")
    edge arrays [E]     replicated (edge list is small relative to [S, N])
    dag     [S, E]      P("batch")

The fixed-point relax loop (`ops.sssp.batched_sssp`) is jitted once over the
mesh; the gather `dist[:, edge_src]` crosses node shards, so XLA emits an
all-gather of each row's node axis over ICI per iteration; the segment-min
writes back sharded.  For S >= devices the batch axis alone gives linear
scaling with no collectives at all — that is the common production shape
(all-sources SPF: S == N).

Reference being replaced: every router redundantly computing SPF on its own
CPU (openr/decision/Decision.cpp:615 buildRouteDb).  Here one *logical*
solver spans chips; results are broadcast host-side via the kvstore layer.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import sssp as ops


def make_mesh(devices=None, batch_axis: int | None = None) -> Mesh:
    """Build a ("batch", "node") mesh over the given (or all) devices.

    `batch_axis` fixes the batch-axis length; default puts all devices on
    the batch axis (the collective-free layout)."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if batch_axis is None:
        batch_axis = n
    if batch_axis <= 0 or n % batch_axis:
        raise ValueError(
            f"make_mesh: {n} devices do not divide into a batch axis of "
            f"{batch_axis} (the node axis would get {n}/{batch_axis} "
            f"devices; pick a batch_axis that divides {n})"
        )
    dev_array = np.asarray(devices).reshape(batch_axis, n // batch_axis)
    return Mesh(dev_array, ("batch", "node"))


def _step_sharded(mesh: Mesh, masked: bool):
    """Shared builder for the jitted full SPF step (distances + SP-DAG)
    with explicit in/out shardings over `mesh` — optionally with a per-row
    edge-exclusion mask (the what-if / KSP batch axis).

    The relaxation runs on the bucketed-ELL tables (ops.batched_sssp_ell);
    the transposed [N, S] distance state is sharded P("node", "batch"), so
    the per-slot row gather all-gathers the node axis over ICI while the
    source batch stays fully parallel."""
    s_batch = NamedSharding(mesh, P("batch"))
    s_mask_t = NamedSharding(mesh, P(None, "batch"))  # allowed_T [E, S]
    s_dist = NamedSharding(mesh, P("batch", "node"))
    s_dist_t = NamedSharding(mesh, P("node", "batch"))
    s_repl = NamedSharding(mesh, P())

    def step(
        sources,
        ell,
        edge_src,
        edge_dst,
        edge_metric,
        edge_up,
        node_overloaded,
        extra_mask_t=None,  # [E_cap, S] bool, False = excluded in that row
    ):
        n_cap = node_overloaded.shape[0]
        allowed_t = ops.make_relax_allowed_T(
            sources, edge_src, edge_up, node_overloaded, extra_mask_t
        )
        if masked:
            allowed_t = jax.lax.with_sharding_constraint(allowed_t, s_mask_t)
        dist0_t = jax.lax.with_sharding_constraint(
            ops.make_dist0_T(sources, ell.new_of_old, n_cap), s_dist_t
        )
        dist_t = ops.batched_sssp_ell(
            dist0_t,
            ell,
            row_allowed_T=allowed_t if masked else None,
            edge_up=edge_up,
            node_overloaded=node_overloaded,
            edge_metric=edge_metric,
        )
        dist_old_t = ops.ell_dist_to_old_T(dist_t, ell)
        dag = ops.sp_dag_mask_from_T(
            dist_old_t, edge_src, edge_dst, edge_metric, allowed_t
        )
        dist = jax.lax.with_sharding_constraint(dist_old_t.T, s_dist)
        return dist, dag

    common = (s_batch, s_repl, s_repl, s_repl, s_repl, s_repl, s_repl)
    if masked:
        return jax.jit(
            step,
            in_shardings=common + (s_mask_t,),
            out_shardings=(s_dist, s_batch),
        )
    return jax.jit(
        lambda *args: step(*args),
        in_shardings=common,
        out_shardings=(s_dist, s_batch),
    )


def spf_step_sharded(mesh: Mesh):
    """Jitted unmasked SPF step (all-sources tiles; collective-free on a
    batch-only mesh)."""
    return _step_sharded(mesh, masked=False)


def sharded_spf_forward(
    mesh: Mesh,
    sources: jax.Array,
    ell,
    edge_src: jax.Array,
    edge_dst: jax.Array,
    edge_metric: jax.Array,
    edge_up: jax.Array,
    node_overloaded: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """One-shot convenience wrapper around `spf_step_sharded`."""
    step = spf_step_sharded(mesh)
    return step(
        sources, ell, edge_src, edge_dst, edge_metric, edge_up, node_overloaded
    )


def whatif_step_sharded(mesh: Mesh):
    """Jitted masked SPF step for failure-scenario fleets: the batch rows
    are (source, exclusion-mask) variants — SRLG what-if at cluster scale.

    Row independence makes the scenario axis embarrassingly parallel:
    rows (and their [S, E] masks, sharded P("batch")) never exchange data,
    so scaling what-if fleets over chips needs no collectives beyond the
    optional node-axis sharding of the distance state."""
    return _step_sharded(mesh, masked=True)


def fleet_product_sharded(
    mesh: Mesh,
    n_sweeps: int,
    n_words: int,
    depth: int = 0,
    resid_rounds: int = 1,
    small_dist: bool = True,
    chord_mode: bool = True,
):
    """Jitted mesh-sharded reduced all-sources product (the round-4/5
    flagship, ops.allsources): the DESTINATION axis P shards over the
    mesh batch axis.

    Sharding layout:
        dest_ids [P]          P("batch")
        dist     [N, P]       P(None, "batch")
        bitmap   [N, P, W]    P(None, "batch", None)
        graph tables / edge state   replicated

    Each shard runs the full banded reverse relax over its own P/D
    destination columns — rolls along the (replicated) node axis and
    residual row gathers are both shard-local, so the relax and the
    bitmap pass emit NO collectives; the only cross-shard ops are the
    verdict's scalar reductions (all(v == d), plus the uint16
    saturation max when small_dist).  This is the multi-chip path for
    fleet products whose destination count outgrows one chip's HBM (the
    [N, P] product + [N, P, W] bitmaps at P=8192/100k nodes is ~4.8 GB —
    two chips' worth with workspace).

    The step body is the SAME single-device pipeline
    (ops.banded.spf_forward_banded want_dag=False/raw_u16/native-layout
    + ops.allsources.ecmp_bitmap_from_reverse_dist) under sharding
    constraints, so semantics changes there reach this path for free."""
    from ..ops import allsources as asrc
    from ..ops.banded import spf_forward_banded

    s_dest = NamedSharding(mesh, P("batch"))
    s_dist = NamedSharding(mesh, P(None, "batch"))
    s_bitmap = NamedSharding(mesh, P(None, "batch", None))
    s_repl = NamedSharding(mesh, P())

    def step(
        dest_ids,  # [P] int32, sharded
        bg,  # BandedGraph pytree, replicated
        r_edge_src,
        r_edge_dst,
        r_edge_metric,
        r_edge_up,
        node_overloaded,
        out,  # OutEll pytree, replicated
        f_edge_metric,
        f_edge_up,
    ):
        dist, _, ok = spf_forward_banded(
            dest_ids,
            bg,
            r_edge_src,
            r_edge_dst,
            r_edge_metric,
            r_edge_up,
            node_overloaded,
            n_supersweeps=n_sweeps,
            depth=depth,
            resid_rounds=resid_rounds,
            small_dist=small_dist,
            want_dag=False,
            chord_mode=chord_mode,
            raw_u16=True,
            transpose=False,
        )
        dist = jax.lax.with_sharding_constraint(dist, s_dist)
        bitmap = asrc.ecmp_bitmap_from_reverse_dist(
            dist, out, f_edge_metric, f_edge_up, node_overloaded, n_words
        )
        return dist, bitmap, ok

    return jax.jit(
        step,
        in_shardings=(
            s_dest,
            s_repl,
            s_repl,
            s_repl,
            s_repl,
            s_repl,
            s_repl,
            s_repl,
            s_repl,
            s_repl,
        ),
        out_shardings=(s_dist, s_bitmap, s_repl),
    )
