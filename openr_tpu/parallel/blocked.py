"""Blocked min-plus APSP over a ("batch", "row", "col") device mesh.

The dest-sharded fleet product (`parallel.mesh.fleet_product_sharded`)
splits the destination axis P, so the node count N is still capped by a
single chip's HBM: every device holds the full [N, P] distance state and
the whole graph mirror.  This module removes that ceiling by sharding
the NODE axis both ways — the classic three-phase blocked
Floyd-Warshall, following the 3-D-tensor accelerator formulation
(PAPERS.md, arxiv 2310.03983), expressed as jitted per-phase kernels
with explicit `NamedSharding`s so XLA inserts the row/col broadcasts.

Layout (the load-bearing trick): the padded Np x Np distance matrix is
held as a 4-D tile tensor

    dist [S, T, B, T, B]    P("batch", None, "row", None, "col")

node g -> (tile t = g // B, lane l = g % B).  The TILE dims stay
UNsharded and the intra-tile LANE dims shard over the mesh, so the
per-round panel extraction `dist[:, k]` / `dist[:, :, :, k]` is a
dynamic-slice on an unsharded dim — purely local, no matter that k is a
traced scalar.  The only collectives are then exactly the textbook
panel broadcasts: the row panel all-gathers its lane dim over "row",
the col panel over "col", and the B x B diagonal tile replicates — per
round O(B * Np) bytes against O(Np^2 / (R*C)) local compute.  The
leading S axis composes with the existing what-if batch: variants stay
embarrassingly parallel over "batch" while N shards both ways.

Per k-round (T = Np / B rounds), with `closed` the masked FW closure of
the diagonal tile:

    phase 1 (diag):   closed = FW(dist[k][k])          replicated
    phase 2 (panels): row' = min(row, closed (*) row)  P(-,-,-,"col")
                      col' = min(col, col (*) closed)  P(-,-,"row",-)
    phase 3 (outer):  dist[k] <- row'; dist[:,:,k] <- col'
                      dist = min(dist, col' (*) row')  rank-B update

where (*) is the min-plus product MASKED at the intermediate: a
contribution through lane m of tile k is dropped (INF) when node m is
overloaded.  That mask IS the fleet drain rule — an overloaded node
relays nothing but remains a valid endpoint (for positive metrics the
relax-kernel exception "blocked as transit unless its distance is 0"
is exactly "excluded as an intermediate") — so the blocked product is
bit-exact against `ops.allsources.reduced_all_sources` after the
int32 normalization.  The panel write-back in phase 3 is REQUIRED
under the mask: the plain-FW shortcut of folding panels into the outer
update assumes the unmasked zero-diagonal argument and silently loses
panel improvements when lanes of tile k are overloaded.

Arithmetic is saturating uint32 min-plus: INF is 1 << 30 (== the int32
INF32 sentinel), finite + finite <= 2^31 never wraps in uint32, and
`min(a + b, INF)` re-saturates — no floats anywhere, per the program
dtype rule.

Lookahead pipelining (the SUMMA/Cannon trick): for multi-round
closures the per-round loop runs `blocked_round_pipelined`, a fused
root that performs round k's write-back + rank-B outer update AND
round k+1's diagonal closure + panel updates in the same program.  The
k+1 panels are derived from the round-k panels restricted to the k+1
slices (integer min-plus is exact, so the restriction is bit-identical
to slicing the full outer update), which makes the k+1 panel
all-gathers data-independent of the round-k outer fori_loop — the
scheduler is then free to run the collectives under the compute.
`parallel.hlo_async` proves that independence from the lowered
module's def-use chains and materializes the async
all-gather-start/done spans.  `OPENR_BLOCKED_PIPELINE=0` forces the
bulk-synchronous loop; any pipelining failure demotes to it
(`mesh.blocked.pipeline_fallbacks`).
"""

from __future__ import annotations

import functools
import math
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..obs import trace as _trace

from ..ops import allsources as asrc

# saturation sentinel: uint32 mirror of the int32 INF32 = 1 << 30 used
# across the decision plane, so the extract is a pure dtype cast
INF32 = 1 << 30
_INFU = np.uint32(INF32)

#: exported through the ctrl handler's `mesh` surface; pre-seeded in
#: __init__ so every key dumps before the first dispatch
BLOCKED_COUNTER_KEYS = (
    "mesh.blocked.products",
    "mesh.blocked.rounds",
    "mesh.blocked.tile_updates",
    "mesh.blocked.panel_broadcasts",
    "mesh.blocked.bytes_exchanged",
    "mesh.blocked.diag_us",
    "mesh.blocked.panel_us",
    "mesh.blocked.outer_us",
    "mesh.blocked.extract_us",
    "mesh.blocked.fallbacks",
    "mesh.blocked.pipeline_rounds_overlapped",
    "mesh.blocked.pipeline_prefetch_issues",
    "mesh.blocked.pipeline_fallbacks",
    "mesh.blocked.pipeline_overlap_frac_est",
)


def make_blocked_mesh(
    devices=None,
    batch: int = 1,
    rows: int | None = None,
    cols: int | None = None,
) -> Mesh:
    """Build the ("batch", "row", "col") mesh over the given (or all)
    devices.  Omitted row/col sizes are factored from the device count
    (squarest split); indivisible requests raise ValueError with the
    numbers spelled out — mesh-shape mismatch is the documented
    graceful-fallback trigger, not an assert."""
    devices = list(devices) if devices is not None else jax.devices()
    n = len(devices)
    if batch <= 0 or n % batch:
        raise ValueError(
            f"blocked mesh: {n} devices do not divide into a batch axis "
            f"of {batch} (need batch * rows * cols == {n})"
        )
    per = n // batch
    if rows is None and cols is None:
        r = max(1, int(math.isqrt(per)))
        while per % r:
            r -= 1
        rows, cols = r, per // r
    elif rows is None:
        if cols <= 0 or per % cols:
            raise ValueError(
                f"blocked mesh: {per} devices per batch slice "
                f"({n} devices / batch={batch}) do not divide into "
                f"cols={cols}"
            )
        rows = per // cols
    elif cols is None:
        if rows <= 0 or per % rows:
            raise ValueError(
                f"blocked mesh: {per} devices per batch slice "
                f"({n} devices / batch={batch}) do not divide into "
                f"rows={rows}"
            )
        cols = per // rows
    if rows <= 0 or cols <= 0 or rows * cols != per:
        raise ValueError(
            f"blocked mesh: rows={rows} x cols={cols} != {per} devices "
            f"per batch slice ({n} devices / batch={batch})"
        )
    dev = np.asarray(devices).reshape(batch, rows, cols)
    return Mesh(dev, ("batch", "row", "col"))


def _sat_minplus(a, b):
    """Saturating uint32 min-plus accumulation term: a + b re-clamped to
    the INF sentinel (a, b <= INF = 2^30, so the uint32 add never
    wraps)."""
    return jnp.minimum(a + b, _INFU)


def _ov_lanes(node_overloaded, k, b: int):
    """[B] bool — drain mask for the lanes of tile k (node g = k*B + l
    blocked as an intermediate when overloaded)."""
    return lax.dynamic_slice_in_dim(node_overloaded, k * b, b)


@functools.partial(jax.jit, static_argnames=("mesh",))
def blocked_diag(dist, node_overloaded, k, *, mesh: Mesh):
    """Phase 1: masked FW closure of the k-th diagonal tile.

    dist [S, T, B, T, B] stays resident; the [S, B, B] tile replicates
    (the only phase-1 exchange).  B sequential rank-1 relaxations —
    work is O(B^3), duplicated on every device by design (cheaper than
    round-tripping a tile that every device needs anyway)."""
    s_repl = NamedSharding(mesh, P("batch"))
    b = dist.shape[2]
    tile = lax.dynamic_index_in_dim(
        lax.dynamic_index_in_dim(dist, k, axis=1, keepdims=False),
        k,
        axis=2,
        keepdims=False,
    )  # [S, B, B]
    tile = lax.with_sharding_constraint(tile, s_repl)
    ov = _ov_lanes(node_overloaded, k, b)

    def body(m, d):
        ov_m = lax.dynamic_index_in_dim(ov, m, axis=0, keepdims=False)
        col_m = lax.dynamic_index_in_dim(d, m, axis=2, keepdims=False)
        row_m = lax.dynamic_index_in_dim(d, m, axis=1, keepdims=False)
        cand = _sat_minplus(col_m[:, :, None], row_m[:, None, :])
        cand = jnp.where(ov_m, _INFU, cand)
        return jnp.minimum(d, cand)

    closed = lax.fori_loop(0, b, body, tile)
    return lax.with_sharding_constraint(closed, s_repl)


@functools.partial(jax.jit, static_argnames=("mesh",))
def blocked_panels(dist, closed, node_overloaded, k, *, mesh: Mesh):
    """Phase 2: update the k-th row and column panels through the closed
    diagonal tile.  The extraction is local (tile dims are unsharded);
    the sharding constraints below are the two panel BROADCASTS — the
    row panel's lane dim all-gathers over "row", the col panel's over
    "col" — after which each min-plus contraction is collective-free."""
    s_row_p = NamedSharding(mesh, P("batch", None, None, "col"))
    s_col_p = NamedSharding(mesh, P("batch", None, "row", None))
    b = dist.shape[2]
    row = lax.dynamic_index_in_dim(dist, k, axis=1, keepdims=False)
    row = lax.with_sharding_constraint(row, s_row_p)  # [S, B, T, B]
    col = lax.dynamic_index_in_dim(dist, k, axis=3, keepdims=False)
    col = lax.with_sharding_constraint(col, s_col_p)  # [S, T, B, B]
    ov = _ov_lanes(node_overloaded, k, b)

    def row_body(m, r):
        ov_m = lax.dynamic_index_in_dim(ov, m, axis=0, keepdims=False)
        c = lax.dynamic_index_in_dim(closed, m, axis=2, keepdims=False)
        rm = lax.dynamic_index_in_dim(row, m, axis=1, keepdims=False)
        cand = _sat_minplus(c[:, :, None, None], rm[:, None, :, :])
        return jnp.minimum(r, jnp.where(ov_m, _INFU, cand))

    def col_body(m, c_acc):
        ov_m = lax.dynamic_index_in_dim(ov, m, axis=0, keepdims=False)
        cm = lax.dynamic_index_in_dim(col, m, axis=3, keepdims=False)
        r = lax.dynamic_index_in_dim(closed, m, axis=1, keepdims=False)
        cand = _sat_minplus(cm[:, :, :, None], r[:, None, None, :])
        return jnp.minimum(c_acc, jnp.where(ov_m, _INFU, cand))

    row_p = lax.fori_loop(0, b, row_body, row)
    col_p = lax.fori_loop(0, b, col_body, col)
    return (
        lax.with_sharding_constraint(row_p, s_row_p),
        lax.with_sharding_constraint(col_p, s_col_p),
    )


@functools.partial(
    jax.jit, static_argnames=("mesh",), donate_argnums=(0,)
)
def blocked_outer(dist, row_p, col_p, node_overloaded, k, *, mesh: Mesh):
    """Phase 3: write the updated panels back, then the rank-B outer
    min-plus update over the whole matrix.  The write-back must come
    first: under the drain mask the outer product does NOT subsume the
    panel positions (the zero-diagonal shortcut of unmasked blocked FW
    breaks when lanes of tile k are overloaded).  Both panels agree on
    the diagonal tile (= closed), so the write order is immaterial."""
    s_dist = NamedSharding(mesh, P("batch", None, "row", None, "col"))
    b = dist.shape[2]
    dist = lax.dynamic_update_index_in_dim(
        dist, lax.with_sharding_constraint(row_p, NamedSharding(
            mesh, P("batch", "row", None, "col"))), k, axis=1
    )
    dist = lax.dynamic_update_index_in_dim(
        dist, lax.with_sharding_constraint(col_p, NamedSharding(
            mesh, P("batch", None, "row", "col"))), k, axis=3
    )
    ov = _ov_lanes(node_overloaded, k, b)

    def body(m, d):
        ov_m = lax.dynamic_index_in_dim(ov, m, axis=0, keepdims=False)
        cm = lax.dynamic_index_in_dim(col_p, m, axis=3, keepdims=False)
        rm = lax.dynamic_index_in_dim(row_p, m, axis=1, keepdims=False)
        cand = _sat_minplus(
            cm[:, :, :, None, None], rm[:, None, None, :, :]
        )
        return jnp.minimum(d, jnp.where(ov_m, _INFU, cand))

    dist = lax.fori_loop(0, b, body, dist)
    return lax.with_sharding_constraint(dist, s_dist)


def _lookahead(nrow, ncol, row_p, col_p, node_overloaded, k, k_next, *, mesh):
    """Round-(k+1) panel prefetch from the round-k panels.

    nrow [S, B, T, B] / ncol [S, T, B, B] are the k+1 panel slices with
    round k's WRITE-BACK already applied (sliced from the written-back
    matrix by the fused root, or emulated by `blocked_lookahead`).
    Three steps, each bit-exact against slicing the bulk-synchronous
    result:

      1. round k's rank-B outer update RESTRICTED to the k+1 slices —
         integer min-plus is exact and order-free, so restricting the
         update to a slab equals slicing the full update;
      2. phase 1 of round k+1: masked FW closure of the next diagonal
         tile (its replication constraint is a collective);
      3. phase 2 of round k+1: panel updates through the closed tile —
         the s_row_p/s_col_p constraints here are THE panel
         all-gathers the pipeline hides under round k's outer loop.

    Nothing in this chain reads the full-matrix outer update, so the
    collectives it issues are provably independent of the round-k
    compute (parallel.hlo_async verifies that from the lowered HLO)."""
    s_repl = NamedSharding(mesh, P("batch"))
    s_row_p = NamedSharding(mesh, P("batch", None, None, "col"))
    s_col_p = NamedSharding(mesh, P("batch", None, "row", None))
    b = row_p.shape[1]
    ov = _ov_lanes(node_overloaded, k, b)
    # the round-k panel blocks facing tile k+1
    colblk = lax.dynamic_index_in_dim(
        col_p, k_next, axis=1, keepdims=False
    )  # [S, B, B]
    rowblk = lax.dynamic_index_in_dim(
        row_p, k_next, axis=2, keepdims=False
    )  # [S, B, B]

    def nrow_body(m, r):
        ov_m = lax.dynamic_index_in_dim(ov, m, axis=0, keepdims=False)
        cm = lax.dynamic_index_in_dim(colblk, m, axis=2, keepdims=False)
        rm = lax.dynamic_index_in_dim(row_p, m, axis=1, keepdims=False)
        cand = _sat_minplus(cm[:, :, None, None], rm[:, None, :, :])
        return jnp.minimum(r, jnp.where(ov_m, _INFU, cand))

    def ncol_body(m, c_acc):
        ov_m = lax.dynamic_index_in_dim(ov, m, axis=0, keepdims=False)
        cm = lax.dynamic_index_in_dim(col_p, m, axis=3, keepdims=False)
        rm = lax.dynamic_index_in_dim(rowblk, m, axis=1, keepdims=False)
        cand = _sat_minplus(cm[:, :, :, None], rm[:, None, None, :])
        return jnp.minimum(c_acc, jnp.where(ov_m, _INFU, cand))

    nrow = lax.fori_loop(0, b, nrow_body, nrow)
    ncol = lax.fori_loop(0, b, ncol_body, ncol)

    # phase 1 of round k+1 on the post-outer diagonal tile
    ov_n = _ov_lanes(node_overloaded, k_next, b)
    tile = lax.dynamic_index_in_dim(nrow, k_next, axis=2, keepdims=False)
    tile = lax.with_sharding_constraint(tile, s_repl)

    def diag_body(m, d):
        ov_m = lax.dynamic_index_in_dim(ov_n, m, axis=0, keepdims=False)
        col_m = lax.dynamic_index_in_dim(d, m, axis=2, keepdims=False)
        row_m = lax.dynamic_index_in_dim(d, m, axis=1, keepdims=False)
        cand = _sat_minplus(col_m[:, :, None], row_m[:, None, :])
        return jnp.minimum(d, jnp.where(ov_m, _INFU, cand))

    closed = lax.fori_loop(0, b, diag_body, tile)
    closed = lax.with_sharding_constraint(closed, s_repl)

    # phase 2 of round k+1 — the constraints below are the panel
    # broadcasts being prefetched
    nrow = lax.with_sharding_constraint(nrow, s_row_p)
    ncol = lax.with_sharding_constraint(ncol, s_col_p)

    def row_body(m, r):
        ov_m = lax.dynamic_index_in_dim(ov_n, m, axis=0, keepdims=False)
        c = lax.dynamic_index_in_dim(closed, m, axis=2, keepdims=False)
        rm = lax.dynamic_index_in_dim(nrow, m, axis=1, keepdims=False)
        cand = _sat_minplus(c[:, :, None, None], rm[:, None, :, :])
        return jnp.minimum(r, jnp.where(ov_m, _INFU, cand))

    def col_body(m, c_acc):
        ov_m = lax.dynamic_index_in_dim(ov_n, m, axis=0, keepdims=False)
        cm = lax.dynamic_index_in_dim(ncol, m, axis=3, keepdims=False)
        r = lax.dynamic_index_in_dim(closed, m, axis=1, keepdims=False)
        cand = _sat_minplus(cm[:, :, :, None], r[:, None, None, :])
        return jnp.minimum(c_acc, jnp.where(ov_m, _INFU, cand))

    nrow_p = lax.fori_loop(0, b, row_body, nrow)
    ncol_p = lax.fori_loop(0, b, col_body, ncol)
    return (
        lax.with_sharding_constraint(nrow_p, s_row_p),
        lax.with_sharding_constraint(ncol_p, s_col_p),
    )


@functools.partial(
    jax.jit, static_argnames=("mesh",), donate_argnums=(0,)
)
def blocked_round_pipelined(dist, row_p, col_p, node_overloaded, k, *, mesh: Mesh):
    """One software-pipelined round: round k's write-back + full rank-B
    outer update, fused with the round-(k+1) panel prefetch.

    The k+1 chain (`_lookahead`) is sliced from the written-back matrix
    BEFORE the outer fori_loop consumes it, so its diagonal replication
    and panel all-gathers have no data dependence on the outer update —
    the scheduler overlaps them (thunk-runtime dataflow on CPU, async
    start/done pairs on TPU; `parallel.hlo_async` materializes the
    spans from the lowered module as evidence).  dist is donated and
    aliases output 0, exactly like `blocked_outer`.  Returns
    (dist', row_p', col_p') — the double-buffered panel carry for the
    next round."""
    s_dist = NamedSharding(mesh, P("batch", None, "row", None, "col"))
    b = dist.shape[2]
    k_next = k + 1
    dist = lax.dynamic_update_index_in_dim(
        dist, lax.with_sharding_constraint(row_p, NamedSharding(
            mesh, P("batch", "row", None, "col"))), k, axis=1
    )
    dist = lax.dynamic_update_index_in_dim(
        dist, lax.with_sharding_constraint(col_p, NamedSharding(
            mesh, P("batch", None, "row", "col"))), k, axis=3
    )
    # k+1 panel slices of the written-back matrix (write-back already
    # covers the round-k corrections the lookahead needs)
    nrow = lax.dynamic_index_in_dim(dist, k_next, axis=1, keepdims=False)
    ncol = lax.dynamic_index_in_dim(dist, k_next, axis=3, keepdims=False)
    nrow_p, ncol_p = _lookahead(
        nrow, ncol, row_p, col_p, node_overloaded, k, k_next, mesh=mesh
    )
    ov = _ov_lanes(node_overloaded, k, b)

    def body(m, d):
        ov_m = lax.dynamic_index_in_dim(ov, m, axis=0, keepdims=False)
        cm = lax.dynamic_index_in_dim(col_p, m, axis=3, keepdims=False)
        rm = lax.dynamic_index_in_dim(row_p, m, axis=1, keepdims=False)
        cand = _sat_minplus(
            cm[:, :, :, None, None], rm[:, None, None, :, :]
        )
        return jnp.minimum(d, jnp.where(ov_m, _INFU, cand))

    dist = lax.fori_loop(0, b, body, dist)
    return lax.with_sharding_constraint(dist, s_dist), nrow_p, ncol_p


@functools.partial(jax.jit, static_argnames=("mesh",))
def blocked_lookahead(dist, row_p, col_p, node_overloaded, k, *, mesh: Mesh):
    """Read-only round-(k+1) panel prefetch for the split pipelined
    round (the Pallas phase-3 rung owns the donation, so the prefetch
    must not consume dist).  Round k's write-back is emulated on the
    two k+1 slices only: the col-tile-k block of the next row panel is
    the round-k col panel's tile-(k+1) block, and symmetrically for
    the next col panel."""
    k_next = k + 1
    nrow = lax.dynamic_index_in_dim(dist, k_next, axis=1, keepdims=False)
    nrow = lax.dynamic_update_index_in_dim(
        nrow,
        lax.dynamic_index_in_dim(col_p, k_next, axis=1, keepdims=False),
        k,
        axis=2,
    )
    ncol = lax.dynamic_index_in_dim(dist, k_next, axis=3, keepdims=False)
    ncol = lax.dynamic_update_index_in_dim(
        ncol,
        lax.dynamic_index_in_dim(row_p, k_next, axis=2, keepdims=False),
        k,
        axis=1,
    )
    return _lookahead(
        nrow, ncol, row_p, col_p, node_overloaded, k, k_next, mesh=mesh
    )


def _outer_pallas_thunk(dist, row_p, col_p, ov, k, interpret: bool):
    """Phase-3 pallas thunk in the run_with_fallback calling shape
    (trailing `interpret` bound by the demotion policy)."""
    from ..ops import pallas_kernels as pk

    return pk.blocked_outer_pallas(
        dist, row_p, col_p, ov, k, interpret=interpret
    )


@functools.partial(jax.jit, static_argnames=("n", "mesh"))
def blocked_extract(dist, tile_id, lane_id, *, n: int, mesh: Mesh):
    """[N, P] int32 destination columns of the S=0 slice: drev[v, p] =
    dist(v -> dest_p), replicated for the host/bitmap consumers.  The
    saturating domain guarantees unreachable == exactly INF32, so the
    cast is bit-exact against the fused product's normalization."""
    sub = dist[0][:, :, tile_id, lane_id]  # [T, B, P]
    t, b, p_dim = sub.shape
    flat = sub.reshape(t * b, p_dim)[:n]
    return lax.with_sharding_constraint(
        flat.astype(jnp.int32), NamedSharding(mesh, P())
    )


@functools.partial(jax.jit, static_argnames=("n_words",))
def _blocked_bitmap(
    drev, out, edge_metric, edge_up, node_overloaded, *, n_words: int
):
    """ECMP bitmap over the blocked product's int32 [N, P] columns —
    the SAME gather-only condition as the fused path
    (ops.allsources.ecmp_bitmap_from_reverse_dist keys on dtype)."""
    return asrc.ecmp_bitmap_from_reverse_dist(
        drev, out, edge_metric, edge_up, node_overloaded, n_words
    )


class BlockedApspEngine:
    """Owns the blocked-APSP mesh, tiling policy and the
    `mesh.blocked.*` accounting — the third dispatch rung behind
    `DeviceResidencyEngine` (delta < fused full < blocked).

    Engagement: `should_engage(n)` — `OPENR_NODE_SHARD=1` forces the
    rung on, `=0` forces it off, otherwise it engages above
    `node_shard_threshold` (the single-chip [N, P]+graph HBM ceiling).
    Mesh shape comes from `OPENR_BLOCKED_MESH` ("RxC" or "BxRxC") or is
    factored from the device count; an indivisible request raises
    ValueError, which the fleet rung converts into a graceful fallback
    to the dest-sharded product (`mesh.blocked.fallbacks`).

    Phase timing counters are dispatch-enqueue attributed (no per-phase
    device sync — a sync per phase would serialize the very pipeline
    being measured); the final extract blocks, so `extract_us` absorbs
    the tail of the device queue."""

    def __init__(
        self,
        parent=None,
        tile: int | None = None,
        node_shard_threshold: int = 1 << 15,
        mesh: Mesh | None = None,
    ) -> None:
        self.counters: dict[str, int] = {k: 0 for k in BLOCKED_COUNTER_KEYS}
        self._parent = parent  # DeviceResidencyEngine (fault_hook owner)
        self.tile = tile
        self.node_shard_threshold = node_shard_threshold
        self._mesh = mesh
        # chaos seam for engine-less use; with a parent, the parent's
        # hook (armed by ChaosSpfBackend) takes precedence so injected
        # faults land mid-run through the same gate as every dispatch
        self.fault_hook = None
        # pinned pipeline override ("0" off / "1" on); None consults
        # OPENR_BLOCKED_PIPELINE — the program auditor pins this
        # attribute instead of env-forcing, like `pallas_mode`
        self.pipeline_mode: str | None = None

    # -- counters -----------------------------------------------------------

    def get_counters(self) -> dict[str, int]:
        return dict(self.counters)

    def _bump(self, name: str, delta: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + delta

    def _hook(self, op: str) -> None:
        hook = self._parent.fault_hook if self._parent is not None else None
        if hook is None:
            hook = self.fault_hook
        if hook is not None:
            hook(op)

    # -- policy -------------------------------------------------------------

    def should_engage(self, n_nodes: int) -> bool:
        force = os.environ.get("OPENR_NODE_SHARD")
        if force == "1":
            return True
        if force == "0":
            return False
        return n_nodes > self.node_shard_threshold

    def mesh(self) -> Mesh:
        if self._mesh is None:
            spec = os.environ.get("OPENR_BLOCKED_MESH", "")
            if spec:
                try:
                    dims = [int(x) for x in spec.lower().split("x")]
                except ValueError:
                    raise ValueError(
                        f"OPENR_BLOCKED_MESH={spec!r}: expected 'RxC' or "
                        f"'BxRxC' integers"
                    ) from None
                if len(dims) == 2:
                    self._mesh = make_blocked_mesh(
                        rows=dims[0], cols=dims[1]
                    )
                elif len(dims) == 3:
                    self._mesh = make_blocked_mesh(
                        batch=dims[0], rows=dims[1], cols=dims[2]
                    )
                else:
                    raise ValueError(
                        f"OPENR_BLOCKED_MESH={spec!r}: expected 2 or 3 "
                        f"'x'-separated sizes, got {len(dims)}"
                    )
            else:
                self._mesh = make_blocked_mesh()
        return self._mesh

    def pipeline_enabled(self, t: int) -> bool:
        """Lookahead pipelining is the default for multi-round
        closures; `OPENR_BLOCKED_PIPELINE=0` (or a pinned
        `pipeline_mode="0"`) forces the bulk-synchronous loop.  A
        single-round closure has nothing to prefetch."""
        if t < 2:
            return False
        mode = self.pipeline_mode
        if mode is None:
            mode = os.environ.get("OPENR_BLOCKED_PIPELINE", "")
        return str(mode) != "0"

    def tile_for(self, n_nodes: int, rows: int, cols: int) -> int:
        """Tile size B: lane dims shard over the mesh, so B must be a
        multiple of lcm(rows, cols); env/ctor overrides are validated
        against that (another graceful-fallback trigger)."""
        base = math.lcm(rows, cols)
        b = self.tile
        if b is None:
            b = int(os.environ.get("OPENR_BLOCKED_TILE", "0")) or None
        if b is None:
            b = base
            while b < 16 and b < max(n_nodes, 1):
                b *= 2
        if b <= 0 or b % base:
            raise ValueError(
                f"blocked tile {b} is not a positive multiple of "
                f"lcm(rows={rows}, cols={cols}) = {base}"
            )
        return b

    # -- staging ------------------------------------------------------------

    @staticmethod
    def dense_dist0(
        n_nodes: int,
        n_pad: int,
        edge_src,
        edge_dst,
        edge_metric,
        edge_up,
        n_edges: int,
    ) -> np.ndarray:
        """[Np, Np] uint32 adjacency in the saturating min-plus domain:
        0 diagonal, min metric over parallel usable edges, INF
        elsewhere.  Padding nodes are isolated (0 self, INF off-diag)
        and never perturb real entries."""
        d0 = np.full((n_pad, n_pad), _INFU, dtype=np.uint32)
        np.fill_diagonal(d0, 0)
        src = np.asarray(edge_src[:n_edges], dtype=np.int64)
        dst = np.asarray(edge_dst[:n_edges], dtype=np.int64)
        met = np.asarray(edge_metric[:n_edges], dtype=np.int64)
        up = np.asarray(edge_up[:n_edges], dtype=bool)
        use = (
            up
            & (src >= 0)
            & (dst >= 0)
            & (src < n_nodes)
            & (dst < n_nodes)
            & (src != dst)
        )
        np.minimum.at(
            d0,
            (src[use], dst[use]),
            np.minimum(met[use], int(_INFU)).astype(np.uint32),
        )
        return d0

    # -- execution ----------------------------------------------------------

    def run_apsp(self, dist0: np.ndarray, node_overloaded: np.ndarray):
        """Run the full blocked closure of dist0 [S, Np, Np] uint32 with
        the [Np] drain mask; returns the device-resident tile tensor
        [S, T, B, T, B] and the (mesh, B) actually used.

        Multi-round closures take the software-pipelined loop by
        default; ANY failure there (chaos fault mid-pipeline, OOM,
        lowering error) bumps `mesh.blocked.pipeline_fallbacks` and
        re-runs the bulk-synchronous loop from the host staging copy —
        safe even though the pipelined rounds donate dist."""
        tr = _trace.TRACE
        if tr is not None:
            tr.annotate("engine.rung", "blocked")
        mesh = self.mesh()
        rows = mesh.shape["row"]
        cols = mesh.shape["col"]
        s, n_pad, _ = dist0.shape
        b = self.tile_for(n_pad, rows, cols)
        if n_pad % b:
            raise ValueError(
                f"blocked APSP: padded node count {n_pad} is not a "
                f"multiple of tile {b}"
            )
        t = n_pad // b
        s_dist = NamedSharding(mesh, P("batch", None, "row", None, "col"))
        ov = jax.device_put(
            np.asarray(node_overloaded, dtype=bool),
            NamedSharding(mesh, P()),
        )
        # modeled exchange per round: each panel's [S, B, Np] lane dim
        # replicates to the (R-1)/(C-1) non-owner rows/cols, the diag
        # tile to everyone
        round_bytes = 4 * s * (
            b * n_pad * (rows - 1) // max(rows, 1)
            + b * n_pad * (cols - 1) // max(cols, 1)
            + b * b
        )
        # Pallas phase-3 rung (ops.pallas_kernels.blocked_outer_pallas):
        # single-device meshes only — the kernel is not shard_map'd, so
        # launching it on a sharded tile tensor would all-gather the
        # matrix.  The parent engine owns the policy, the
        # device.engine.pallas_* accounting and the chaos seam; a
        # standalone rung (no parent) always takes the XLA phase.
        run_pallas = (
            getattr(self._parent, "run_pallas", None)
            if mesh.devices.size == 1
            else None
        )
        # the split lookahead+outer rounds exist only to order the
        # Pallas donation; when the kernels resolve to "off" the
        # pipelined loop keeps the fused blocked_round_pipelined root
        # (the epilogue still dispatches through run_pallas, so the
        # pallas_skips accounting survives)
        split_rounds = False
        if run_pallas is not None:
            from ..ops import pallas_kernels as pk

            eff = getattr(self._parent, "pallas_mode", None)
            split_rounds = (
                eff if eff is not None else pk.pallas_mode()
            ) != "off"
        if self.pipeline_enabled(t):
            dist = jax.device_put(dist0.reshape(s, t, b, t, b), s_dist)
            try:
                return (
                    self._rounds_pipelined(
                        dist, ov, t, mesh, run_pallas, round_bytes,
                        split_rounds,
                    ),
                    b,
                )
            except Exception:
                # the pipelined rounds donate dist, so the device copy
                # may be gone — demote to bulk from the host staging
                self._bump("mesh.blocked.pipeline_fallbacks")
        dist = jax.device_put(dist0.reshape(s, t, b, t, b), s_dist)
        return (
            self._rounds_bulk(dist, ov, t, mesh, run_pallas, round_bytes),
            b,
        )

    def _outer_step(self, dist, row_p, col_p, ov, kk, mesh, run_pallas):
        """Round-k phase 3 through the dispatch rung: Pallas with the
        XLA thunk as the demotion target, or plain `blocked_outer`."""
        if run_pallas is not None:
            # every demotion trigger raises at/before trace time
            # (pallas_kernels.blocked_outer_pallas docstring), so
            # the donated dist is still intact for the XLA thunk
            return run_pallas(
                "outer",
                functools.partial(
                    _outer_pallas_thunk, dist, row_p, col_p, ov, kk
                ),
                functools.partial(
                    blocked_outer, dist, row_p, col_p, ov, kk, mesh=mesh
                ),
            )
        return blocked_outer(dist, row_p, col_p, ov, kk, mesh=mesh)

    def _rounds_bulk(self, dist, ov, t, mesh, run_pallas, round_bytes):
        """The bulk-synchronous round loop: every round serializes
        diag closure -> panel broadcasts -> outer update."""
        for k in range(t):
            self._hook("blocked_round")
            kk = jnp.int32(k)
            t0 = time.monotonic_ns()
            closed = blocked_diag(dist, ov, kk, mesh=mesh)
            t1 = time.monotonic_ns()
            row_p, col_p = blocked_panels(dist, closed, ov, kk, mesh=mesh)
            t2 = time.monotonic_ns()
            dist = self._outer_step(
                dist, row_p, col_p, ov, kk, mesh, run_pallas
            )
            t3 = time.monotonic_ns()
            self._bump("mesh.blocked.tile_updates")
            self._bump("mesh.blocked.panel_broadcasts", 2)
            self._bump("mesh.blocked.bytes_exchanged", round_bytes)
            self._bump("mesh.blocked.diag_us", (t1 - t0) // 1000)
            self._bump("mesh.blocked.panel_us", (t2 - t1) // 1000)
            self._bump("mesh.blocked.outer_us", (t3 - t2) // 1000)
        self._bump("mesh.blocked.rounds", t)
        return dist

    def _rounds_pipelined(
        self, dist, ov, t, mesh, run_pallas, round_bytes, split_rounds=False
    ):
        """The software-pipelined round loop (t >= 2): the panels are
        double-buffered — each round consumes panels[k] and produces
        panels[k+1] while the round-k outer update runs, so the panel
        all-gathers hide under compute.  The prologue computes
        panels[0] the bulk way (nothing to overlap them with yet); the
        epilogue round has no next panel to prefetch and runs the plain
        outer step."""
        multi = mesh.devices.size > 1
        k0 = jnp.int32(0)
        t0 = time.monotonic_ns()
        closed = blocked_diag(dist, ov, k0, mesh=mesh)
        t1 = time.monotonic_ns()
        row_p, col_p = blocked_panels(dist, closed, ov, k0, mesh=mesh)
        t2 = time.monotonic_ns()
        self._bump("mesh.blocked.diag_us", (t1 - t0) // 1000)
        self._bump("mesh.blocked.panel_us", (t2 - t1) // 1000)
        for k in range(t - 1):
            self._hook("blocked_round")
            kk = jnp.int32(k)
            t2 = time.monotonic_ns()
            if split_rounds:
                # split round: the read-only prefetch is enqueued
                # first, then the Pallas outer consumes (donates) dist
                nrow_p, ncol_p = blocked_lookahead(
                    dist, row_p, col_p, ov, kk, mesh=mesh
                )
                dist = self._outer_step(
                    dist, row_p, col_p, ov, kk, mesh, run_pallas
                )
            else:
                dist, nrow_p, ncol_p = blocked_round_pipelined(
                    dist, row_p, col_p, ov, kk, mesh=mesh
                )
            t3 = time.monotonic_ns()
            row_p, col_p = nrow_p, ncol_p
            self._bump("mesh.blocked.tile_updates")
            self._bump("mesh.blocked.panel_broadcasts", 2)
            self._bump("mesh.blocked.bytes_exchanged", round_bytes)
            self._bump("mesh.blocked.outer_us", (t3 - t2) // 1000)
            self._bump("mesh.blocked.pipeline_prefetch_issues")
            if multi:
                # only a multi-device mesh has collectives to hide; on
                # the degenerate 1-device mesh the prefetch is pure
                # compute reordering
                self._bump("mesh.blocked.pipeline_rounds_overlapped")
        # epilogue: the final round's panels were prefetched by the
        # previous round — only the outer update remains
        self._hook("blocked_round")
        kk = jnp.int32(t - 1)
        t2 = time.monotonic_ns()
        dist = self._outer_step(dist, row_p, col_p, ov, kk, mesh, run_pallas)
        t3 = time.monotonic_ns()
        self._bump("mesh.blocked.tile_updates")
        self._bump("mesh.blocked.panel_broadcasts", 2)
        self._bump("mesh.blocked.bytes_exchanged", round_bytes)
        self._bump("mesh.blocked.outer_us", (t3 - t2) // 1000)
        self._bump("mesh.blocked.rounds", t)
        # gauge: modeled fraction of rounds whose collectives overlap
        # compute (prologue gathers and the 1-device mesh overlap none)
        self.counters["mesh.blocked.pipeline_overlap_frac_est"] = (
            100 * (t - 1) // t if multi else 0
        )
        return dist

    def fleet_product(self, csr, dest_ids: np.ndarray, out):
        """The fleet-product face of the rung: forward-graph blocked
        APSP, destination-column extract, ECMP bitmap.  Returns
        (dist [N, P] int32, bitmap [N, P, W] uint32, True) matching the
        `reduced_all_sources` contract shape the fleet view stores."""
        self._hook("blocked_product")
        n = int(csr.n_nodes)
        mesh = self.mesh()
        b = self.tile_for(n, mesh.shape["row"], mesh.shape["col"])
        n_pad = -(-n // b) * b
        d0 = self.dense_dist0(
            n,
            n_pad,
            csr.edge_src,
            csr.edge_dst,
            csr.edge_metric,
            csr.edge_up,
            int(csr.n_edges),
        )
        ov_pad = np.zeros(n_pad, dtype=bool)
        ov_pad[:n] = np.asarray(csr.node_overloaded[:n], dtype=bool)
        dist, b = self.run_apsp(d0[None], ov_pad)
        t0 = time.monotonic_ns()
        dest = np.asarray(dest_ids, dtype=np.int32)
        drev = blocked_extract(
            dist,
            jnp.asarray(dest // b, dtype=jnp.int32),
            jnp.asarray(dest % b, dtype=jnp.int32),
            n=n,
            mesh=mesh,
        )
        bitmap = _blocked_bitmap(
            drev,
            out,
            jnp.asarray(csr.edge_metric),
            jnp.asarray(csr.edge_up),
            jnp.asarray(csr.node_overloaded),
            n_words=out.n_words,
        )
        # one deliberate sync: the product is complete here and the
        # enqueue-attributed phase timers need a closing edge
        jax.block_until_ready(bitmap)
        self._bump(
            "mesh.blocked.extract_us",
            (time.monotonic_ns() - t0) // 1000,
        )
        self._bump("mesh.blocked.products")
        return drev, bitmap, True
