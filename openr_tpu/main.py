"""Composition root: build and run the full daemon.

Functional equivalent of the reference's main() (openr/Main.cpp:165-688):
create the replicate queues, start every module in dependency order, wire
the ctrl server over all of them, and tear down in reverse order.

`OpenrDaemon` is both the daemon entry (`python -m openr_tpu.main --config
cfg.json`) and the in-process multi-node test harness (the OpenrWrapper
pattern, openr/tests/OpenrWrapper.h:38): pass a MockIoProvider endpoint and
an in-process KvStore fabric to run N daemons in one process with no
network or kernel.

Queue wiring (reference: Main.cpp:275-287; SURVEY §1 dataflow):

    netlink -> netlinkEventsQueue ----------------> LinkMonitor
    LinkMonitor -> interfaceUpdatesQueue ---------> Spark
    Spark -> neighborUpdatesQueue ----------------> LinkMonitor
    LinkMonitor -> peerUpdatesQueue --------------> KvStore
    LinkMonitor/allocator -> prefixUpdatesQueue --> PrefixManager
    PrefixManager/LinkMonitor -> (client) --------> KvStore
    KvStore -> kvStoreUpdatesQueue ---------------> Decision, clients
    KvStore -> kvStoreSyncEventsQueue ------------> LinkMonitor
    Decision -> routeUpdatesQueue ----------------> Fib, PrefixManager
    Fib -> fibUpdatesQueue -----------------------> ctrl streaming
    everyone -> logSampleQueue -------------------> Monitor
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading
from typing import Optional

from .analysis import race as _race
from .config import OpenrConfig, load_config
from .ctrl import CtrlServer, OpenrCtrlHandler, TcpKvStoreTransport
from .decision.decision import Decision
from .decision.spf_solver import DeviceSpfBackend, SpfBackend
from .fib import Fib, FibAgent, MockFibAgent
from .config_store import PersistentStore
from .kvstore import KvStore, KvStoreClientInternal, KvStoreFilters
from .link_monitor import LinkMonitor
from .monitor import Monitor, Watchdog
from .prefix_manager import PrefixManager
from .allocators import PrefixAllocator
from .runtime.queue import ReplicateQueue
from .spark import IoProvider, Spark, UdpIoProvider

log = logging.getLogger(__name__)


def _obs_stats():
    """The tracing surface (obs.* counters + dumpTraces/getSpanSamples).
    ObsStats reads the tracer late-bound, so the daemon answers zeroed
    counters and empty trace lists when OPENR_TRACE is off."""
    from .obs import ObsStats

    return ObsStats()


def _fuzz_counters():
    """The chaos fuzzer's process-wide counter registry (chaos.fuzz.*,
    pre-seeded zeros).  Imported lazily: the daemon hot path never needs
    the fuzzer's harness machinery, only its counter surface."""
    from .chaos.fuzz import FUZZ_COUNTERS

    return FUZZ_COUNTERS


def _sched_counters():
    """The schedule explorer's process-wide counter registry (sched.*,
    pre-seeded zeros).  Same contract as _fuzz_counters: a daemon that
    never explores still answers the whole family on both wires."""
    from .analysis.sched import SCHED_COUNTERS

    return SCHED_COUNTERS


def _snapshot_counters():
    """The engine-snapshot registry (snapshot.*, pre-seeded zeros).
    Same contract as _fuzz_counters: a daemon that never takes or
    restores a snapshot still answers the whole family on both wires."""
    from .snapshot import SNAPSHOT_COUNTERS

    return SNAPSHOT_COUNTERS


class OpenrDaemon:
    def __init__(
        self,
        config: OpenrConfig,
        *,
        io_provider: Optional[IoProvider] = None,
        kvstore_transport=None,
        fib_agent: Optional[FibAgent] = None,
        netlink_events_queue: Optional[ReplicateQueue] = None,
        spf_backend: Optional[SpfBackend] = None,
        # Device SPF is the default: DeviceSpfBackend itself serves tiny
        # topologies (< min_device_nodes) from the host Dijkstra memo, so
        # the flag only matters to force pure-host behavior.
        use_device_spf: bool = True,
        ctrl_port: Optional[int] = None,
        spark_v6_addr: str = "",
    ) -> None:
        # OPENR_TSAN=1 arms the happens-before race detector before any
        # module object exists (no-op otherwise; docs/OPERATIONS.md)
        _race.maybe_enable()
        self.config = config
        name = config.node_name
        areas = config.area_ids

        # -- queues (reference: Main.cpp:275-287) ----------------------------
        self.kvstore_updates_queue: ReplicateQueue = ReplicateQueue()
        self.kvstore_sync_events_queue: ReplicateQueue = ReplicateQueue()
        self.interface_updates_queue: ReplicateQueue = ReplicateQueue()
        self.neighbor_updates_queue: ReplicateQueue = ReplicateQueue()
        self.peer_updates_queue: ReplicateQueue = ReplicateQueue()
        self.prefix_updates_queue: ReplicateQueue = ReplicateQueue()
        self.route_updates_queue: ReplicateQueue = ReplicateQueue()
        self.static_routes_queue: ReplicateQueue = ReplicateQueue()
        self.fib_updates_queue: ReplicateQueue = ReplicateQueue()
        self.log_sample_queue: ReplicateQueue = ReplicateQueue()
        self.netlink_events_queue = netlink_events_queue or ReplicateQueue()
        self._queues = {
            "kvstore_updates": self.kvstore_updates_queue,
            "kvstore_sync_events": self.kvstore_sync_events_queue,
            "interface_updates": self.interface_updates_queue,
            "neighbor_updates": self.neighbor_updates_queue,
            "peer_updates": self.peer_updates_queue,
            "prefix_updates": self.prefix_updates_queue,
            "route_updates": self.route_updates_queue,
            "static_routes": self.static_routes_queue,
            "fib_updates": self.fib_updates_queue,
            "log_sample": self.log_sample_queue,
            # found by thread-queue-registration: the netlink event stream
            # was invisible to queue.* counters and the shutdown drain
            "netlink_events": self.netlink_events_queue,
        }

        # -- watchdog (reference: Main.cpp:295-300) --------------------------
        self.watchdog: Optional[Watchdog] = None
        if config.enable_watchdog:
            wc = config.watchdog_config
            self.watchdog = Watchdog(
                interval_s=wc.interval_s,
                thread_timeout_s=wc.thread_timeout_s,
                max_memory_bytes=wc.max_memory_mb * 1024 * 1024,
            )

        # -- config store (reference: Main.cpp:370-375) ----------------------
        self.config_store = PersistentStore(
            config.persistent_config_store_path or f"/tmp/openr_tpu_{name}.bin",
            dryrun=not config.persistent_config_store_path,
        )

        # -- monitor ---------------------------------------------------------
        self.monitor = Monitor(name, self.log_sample_queue.get_reader())

        # -- kvstore (reference: Main.cpp:389-408) ---------------------------
        kvc = config.kvstore_config
        self.kvstore = KvStore(
            name,
            self.kvstore_updates_queue,
            self.kvstore_sync_events_queue,
            self.peer_updates_queue.get_reader(),
            transport=kvstore_transport
            or TcpKvStoreTransport(
                default_port=config.openr_ctrl_port, tls=self._tls_config()
            ),
            areas=areas,
            filters=(
                KvStoreFilters(kvc.key_prefix_filters)
                if kvc.key_prefix_filters
                else None
            ),
            flood_rate=(
                (kvc.flood_msg_per_sec, kvc.flood_msg_burst_size)
                if kvc.flood_msg_per_sec > 0
                else None
            ),
            ttl_decr_ms=kvc.ttl_decrement_ms,
            enable_flood_optimization=kvc.enable_flood_optimization,
            is_flood_root=kvc.is_flood_root,
        )

        # -- spark (reference: Main.cpp:443-456) -----------------------------
        self.io_provider = io_provider or UdpIoProvider()
        self.spark = Spark(
            name,
            self.interface_updates_queue.get_reader(),
            self.neighbor_updates_queue,
            self.io_provider,
            config=config.spark_timers(),
            areas=config.spark_area_configs(),
            domain=config.domain,
            ctrl_port=ctrl_port or config.openr_ctrl_port,
            v6_addr=spark_v6_addr,
        )

        # -- link monitor (reference: Main.cpp:458-478) ----------------------
        lmc = config.link_monitor_config
        self.link_monitor = LinkMonitor(
            name,
            interface_updates_queue=self.interface_updates_queue,
            peer_updates_queue=self.peer_updates_queue,
            prefix_updates_queue=self.prefix_updates_queue,
            neighbor_updates=self.neighbor_updates_queue.get_reader(),
            kvstore_sync_events=self.kvstore_sync_events_queue.get_reader(),
            netlink_events=self.netlink_events_queue.get_reader(),
            config_store=self.config_store,
            areas=areas,
            node_label=config.node_label,
            enable_rtt_metric=lmc.use_rtt_metric,
            include_if_regexes=tuple(lmc.include_interface_regexes),
            exclude_if_regexes=tuple(lmc.exclude_interface_regexes),
            redistribute_if_regexes=tuple(lmc.redistribute_interface_regexes),
            assume_drained=config.assume_drained,
            override_drain_state=config.override_drain_state,
        )

        # -- decision (reference: Main.cpp:518-531) --------------------------
        backend = spf_backend or (DeviceSpfBackend() if use_device_spf else None)
        dc = config.decision_config
        self.decision = Decision(
            name,
            self.kvstore_updates_queue.get_reader(),
            self.static_routes_queue.get_reader(),
            self.route_updates_queue,
            debounce_min_s=dc.debounce_min_ms / 1000.0,
            debounce_max_s=dc.debounce_max_ms / 1000.0,
            eor_time_s=config.eor_time_s,
            enable_v4=config.enable_v4,
            enable_ordered_fib=config.enable_ordered_fib_programming,
            enable_best_route_selection=config.enable_best_route_selection,
            enable_rib_policy=config.enable_rib_policy,
            spf_backend=backend,
            # the incremental delta rung needs an engine to dispatch
            # through; daemons running the device backend get it, forced
            # pure-host daemons keep the legacy paths (it would only
            # gate-fail per rebuild).  Inert below delta_min_p dests.
            fleet_delta=use_device_spf if spf_backend is None else None,
        )

        # -- fib (reference: Main.cpp:533-545) -------------------------------
        if fib_agent is None and config.fib_agent_port:
            from .platform import TcpFibAgent

            fib_agent = TcpFibAgent(
                host=config.fib_agent_host, port=config.fib_agent_port
            )
        self.fib_agent = fib_agent or MockFibAgent()
        self.fib = Fib(
            name,
            self.route_updates_queue.get_reader(),
            self.fib_agent,
            fib_updates_queue=self.fib_updates_queue,
            log_sample_queue=self.log_sample_queue,
            dryrun=config.dryrun,
            enable_segment_routing=config.enable_segment_routing,
        )

        # modules created after start(): client-dependent ones
        self.kvstore_client: Optional[KvStoreClientInternal] = None
        self.prefix_manager: Optional[PrefixManager] = None
        self.prefix_allocator: Optional[PrefixAllocator] = None
        self.serving = None  # serving.QueryScheduler (started in start())
        self.ctrl_server: Optional[CtrlServer] = None
        self.thrift_shim = None  # interop.shim.ThriftBinaryShim when enabled
        self._plugin = None
        self._plugin_handle = None
        self.netlink = None
        self._ctrl_port_override = ctrl_port
        self._started = False

    # -- lifecycle (reference: Main.cpp startup order + reverse teardown) ----

    def start(self) -> None:
        assert not self._started
        self._started = True
        # netlink FIRST so the initial kernel state replay is queued before
        # LinkMonitor starts consuming (reference: Main.cpp:330-343 brings
        # the netlink evb up before every module)
        if self.config.enable_netlink:
            from .nl import NetlinkProtocolSocket

            self.netlink = NetlinkProtocolSocket(self.netlink_events_queue)
            self.netlink.run()
        modules = [self.monitor, self.kvstore, self.spark, self.link_monitor]
        for module in modules:
            module.run()
            if self.watchdog is not None:
                self.watchdog.add_evb(module)

        # kvstore client lives on the link-monitor evb (its main user)
        self.kvstore_client = KvStoreClientInternal(
            self.link_monitor,
            self.config.node_name,
            self.kvstore,
            self.kvstore_updates_queue.get_reader(),
        )
        # composition-root wiring: single startup assignment, read only by
        # work scheduled onto the link-monitor loop after this point
        # openr: disable=thread-cross-module-write
        self.link_monitor.kvstore_client = self.kvstore_client

        self.prefix_manager = PrefixManager(
            self.config.node_name,
            self.kvstore_client,
            prefix_updates=self.prefix_updates_queue.get_reader(),
            route_updates=self.route_updates_queue.get_reader(),
            areas=self.config.area_ids,
        )
        self.prefix_manager.run()

        if self.config.prefix_allocation_config is not None:
            pac = self.config.prefix_allocation_config
            self.prefix_allocator = PrefixAllocator(
                self.link_monitor,
                self.config.node_name,
                self.kvstore_client,
                pac.seed_prefix,
                pac.allocate_prefix_len,
                area=self.config.area_ids[0],
                prefix_updates_queue=self.prefix_updates_queue,
                config_store=self.config_store,
                assign_to_interface=pac.assign_to_interface,
            )
            self.prefix_allocator.start()

        # plugin (BGP-speaker seam) BEFORE Decision so its origins are in
        # place for the first SPF (reference: Main.cpp:501-510)
        if self.config.plugin_module:
            from .plugin import PluginArgs, load_plugin, plugin_start

            module = load_plugin(self.config.plugin_module)
            self._plugin_handle = plugin_start(
                module,
                PluginArgs(
                    prefix_updates_queue=self.prefix_updates_queue,
                    static_routes_update_queue=self.static_routes_queue,
                    route_updates_queue=self.route_updates_queue.get_reader(),
                    config=self.config,
                    node_name=self.config.node_name,
                ),
            )
            # recorded only after a successful start so a plugin_start
            # failure doesn't make stop() call plugin_stop(module, None)
            self._plugin = module

        # decision AFTER kvstore/link-monitor so SPF sees self
        # (reference: Main.cpp:518 comment)
        self.decision.run()
        self.fib.run()
        for module in (self.prefix_manager, self.decision, self.fib):
            if self.watchdog is not None:
                self.watchdog.add_evb(module)

        # serving layer BEFORE the wire surfaces that submit into it:
        # queries marshal onto the Decision thread in coalesced batches
        # (serving.DecisionBatchBackend), so Decision must already be up
        from .serving import DecisionBatchBackend, QueryScheduler

        self.serving = QueryScheduler(
            DecisionBatchBackend(self.decision),
            # hold freshly coalesced batches (bounded) while topology
            # events are mid-fold, so they pin the post-storm epoch
            defer_hint=self.decision.pending_event_hint,
        )
        self.serving.run()
        if self.watchdog is not None:
            self.watchdog.add_evb(self.serving)
        # admission-queue stats ride the queue.* counter surface next to
        # the inter-module fabric (queue.serving_admission.overflows is
        # the first overload signal; see docs/OPERATIONS.md)
        self._queues["serving_admission"] = self.serving.admission

        handler = OpenrCtrlHandler(
            self.config.node_name,
            kvstore=self.kvstore,
            decision=self.decision,
            fib=self.fib,
            link_monitor=self.link_monitor,
            prefix_manager=self.prefix_manager,
            spark=self.spark,
            monitor=self.monitor,
            netlink=self.netlink,
            config=self.config,
            # device-residency engine counters (device.engine.*) ride the
            # same getCounters surface as every module's
            device=getattr(self.decision.spf_solver.spf, "engine", None),
            # node-sharding rung counters (mesh.blocked.*) ride along;
            # pre-seeded at engine construction so they dump before the
            # first blocked dispatch
            mesh=getattr(
                getattr(self.decision.spf_solver.spf, "engine", None),
                "blocked",
                None,
            ),
            serving=self.serving,
            # TE optimizer counters (te.*, pre-seeded at construction)
            # ride the same surface; the optimizer lives on the serving
            # backend so optimizeMetrics runs and counter reads agree
            te=getattr(self.serving.backend, "te", None),
            # chaos fuzzer counters (chaos.fuzz.*, pre-seeded zeros at
            # module import) ride the same surface: a daemon that never
            # fuzzes still answers the whole family, and an in-process
            # fuzz session's runs/shrinks are visible on both wires
            fuzz=_fuzz_counters(),
            # schedule-explorer counters (sched.*, pre-seeded zeros at
            # module import) ride the same surface: exploration sessions'
            # schedules/prunes/replays are visible on both wires
            sched=_sched_counters(),
            # trace-span surface (obs.*, zeroed when OPENR_TRACE is off):
            # same wire shape armed or not, plus dumpTraces/getSpanSamples
            obs=_obs_stats(),
            # engine-snapshot counters (snapshot.*, pre-seeded zeros at
            # module import): takes/restores/replays visible on both wires
            snapshot=_snapshot_counters(),
            kvstore_updates_queue=self.kvstore_updates_queue,
            fib_updates_queue=self.fib_updates_queue,
            config_store=self.config_store,
            watchdog=self.watchdog,
            queues=self._queues,
        )
        self.ctrl_server = CtrlServer(
            handler,
            host=self.config.listen_addr,
            port=(
                self._ctrl_port_override
                if self._ctrl_port_override is not None
                else self.config.openr_ctrl_port
            ),
            tls=self._tls_config(),
        )
        self.ctrl_server.run()
        if self.config.thrift_shim_port:
            # stock-openr-shaped thrift Binary+framed listener over the
            # same KvStore (openr_tpu.interop.shim)
            from .interop.shim import ThriftBinaryShim

            self.thrift_shim = ThriftBinaryShim(
                self.kvstore,
                host=self.config.listen_addr,
                port=max(self.config.thrift_shim_port, 0),
                node_name=self.config.node_name,
                decision=self.decision,
                fib=self.fib,
                serving=self.serving,
                counters_fn=self.ctrl_server.handler._all_counters,
                kvstore_updates_queue=self.kvstore_updates_queue,
            )
            self.thrift_shim.run()
        if self.watchdog is not None:
            self.watchdog.add_evb(self.ctrl_server)
            self.watchdog.start()

    def _tls_config(self):
        """config.TlsConf -> ctrl.tls.TlsConfig (None when TLS is off)."""
        tc = self.config.tls_config
        if tc is None or not tc.cert_path:
            return None
        from .ctrl.tls import TlsConfig

        return TlsConfig(
            cert_path=tc.cert_path,
            key_path=tc.key_path,
            ca_path=tc.ca_path,
            acl_regex=tc.acl_regex,
        )

    @property
    def ctrl_port(self) -> int:
        assert self.ctrl_server is not None
        return self.ctrl_server.port

    def stop(self) -> None:
        """Reverse-order teardown (reference: Main.cpp:617-668)."""
        if self._plugin is not None:
            from .plugin import plugin_stop

            plugin_stop(self._plugin, self._plugin_handle)
            self._plugin = None
        if self.watchdog is not None:
            self.watchdog.stop()
        for queue in self._queues.values():
            queue.close()
        modules = [
            self.thrift_shim,
            self.ctrl_server,
            # serving after its wire surfaces (no new submissions), before
            # the Decision thread its batches marshal onto
            self.serving,
            self.fib,
            self.decision,
            self.prefix_manager,
            self.link_monitor,
            self.spark,
            self.kvstore,
            self.monitor,
        ]
        if self.prefix_allocator is not None:
            self.prefix_allocator.stop()
        if self.kvstore_client is not None:
            self.kvstore_client.stop()
        for module in modules:
            if module is not None:
                module.stop()
        for module in modules:
            if module is not None:
                module.wait_until_stopped(5)
        if self.netlink is not None:
            self.netlink.stop()
            self.netlink.wait_until_stopped(5)
            self.netlink = None
        close_agent = getattr(self.fib_agent, "close", None)
        if callable(close_agent):
            close_agent()  # TcpFibAgent holds a persistent socket
        self.config_store.close()


def fleet_node_config(name: str, ctrl_port: int = 0) -> OpenrConfig:
    """Fast-timer config for an in-process serving-fleet replica (the
    OpenrWrapper posture: mock fabrics, no watchdog, sub-second Spark)."""
    from .config import AreaConf, DecisionConf, SparkConf

    return OpenrConfig(
        node_name=name,
        areas=[AreaConf()],
        openr_ctrl_port=ctrl_port,
        spark_config=SparkConf(
            hello_time_s=0.3,
            fastinit_hello_time_ms=20,
            keepalive_time_s=0.05,
            hold_time_s=0.5,
            graceful_restart_time_s=1.0,
        ),
        decision_config=DecisionConf(debounce_min_ms=5, debounce_max_ms=20),
        enable_watchdog=False,
        node_label=0,
    ).validate()


class ServingFleet:
    """K full daemons in one process, peered over a KvStore full-mesh and
    fronted by one serving.ReplicaRouter — the replica-fleet serving
    posture (docs/ARCHITECTURE.md "Replica fleet").

    Every daemon runs the whole stack (Spark adjacency over a mock
    fabric, KvStore flooding, Decision, serving.QueryScheduler), so each
    replica independently converges to the same LinkState version and can
    answer any query at its current epoch.  The router spreads queries
    across the K schedulers with per-session epoch pinning, health-aware
    failover, and bounded hedging; `handler` is the front-door
    OpenrCtrlHandler whose queryPaths/queryWhatIf/queryKsp go through the
    router, so the fleet looks like one daemon to ctrl clients while
    serving.router.* counters expose the spread.
    """

    def __init__(
        self,
        k: int = 3,
        *,
        node_prefix: str = "fleet",
        hedge_after_s: float = 0.05,
        config_fn=None,
        spf_backend: Optional[SpfBackend] = None,
        use_device_spf: bool = True,
    ) -> None:
        from .kvstore import InProcessTransport
        from .spark import MockIoProvider

        if k < 1:
            raise ValueError("ServingFleet needs at least one replica")
        self._make = config_fn or fleet_node_config
        self._node_prefix = node_prefix
        self._spf_backend = spf_backend
        self._use_device_spf = use_device_spf
        self.spark_fabric = MockIoProvider()
        self.kv_fabric = InProcessTransport()
        self.daemons: list[OpenrDaemon] = []
        self._names: list[str] = []
        # creation index per live daemon: interface names (if-{i}-{j}) and
        # mock addresses are minted from it and never reused, so a
        # scale-in followed by a scale-out can't collide with the fabric
        # state the departed replica left behind
        self._indices: list[int] = []
        self._next_idx = 0
        for _ in range(k):
            self._new_daemon()
        self._hedge_after_s = hedge_after_s
        self.router = None  # serving.ReplicaRouter (built in start())
        self.handler = None  # front-door OpenrCtrlHandler over the router

    def _new_daemon(self) -> "OpenrDaemon":
        """Mint the next replica (not yet started or meshed)."""
        i = self._next_idx
        self._next_idx += 1
        name = f"{self._node_prefix}-{i}"
        addr = f"fe80::{name}"
        daemon = OpenrDaemon(
            self._make(name),
            io_provider=self.spark_fabric.endpoint(name),
            kvstore_transport=self.kv_fabric.bind(addr),
            spark_v6_addr=addr,
            spf_backend=self._spf_backend,
            use_device_spf=self._use_device_spf,
        )
        self.kv_fabric.register(addr, daemon.kvstore)
        self.daemons.append(daemon)
        self._names.append(name)
        self._indices.append(i)
        return daemon

    def start(self) -> None:
        from .serving import ReplicaRouter, SchedulerReplica
        from .types import LinkEvent

        for daemon in self.daemons:
            daemon.start()
        # full-mesh adjacency: every replica peers with every other, so
        # one surviving replica keeps the whole fleet's KvStore coherent
        # through any single partition
        k = len(self.daemons)
        for i in range(k):
            for j in range(i + 1, k):
                self.spark_fabric.connect(
                    self._names[i],
                    f"if-{i}-{j}",
                    self._names[j],
                    f"if-{j}-{i}",
                )
        for i, daemon in enumerate(self.daemons):
            for j in range(k):
                if j == i:
                    continue
                daemon.netlink_events_queue.push(
                    LinkEvent(f"if-{i}-{j}", j + 1, True)
                )
        self.router = ReplicaRouter(
            [
                SchedulerReplica(self._names[i], d.serving)
                for i, d in enumerate(self.daemons)
            ],
            hedge_after_s=self._hedge_after_s if k > 1 else None,
        )
        # front door: daemon 0's introspection surfaces plus the router
        # as the serving module — queryPaths et al spread over the fleet
        front = self.daemons[0]
        self.handler = OpenrCtrlHandler(
            f"{self._names[0]}-front",
            kvstore=front.kvstore,
            decision=front.decision,
            fib=front.fib,
            link_monitor=front.link_monitor,
            prefix_manager=front.prefix_manager,
            spark=front.spark,
            monitor=front.monitor,
            config=front.config,
            serving=self.router,
            sched=_sched_counters(),
            obs=_obs_stats(),
            snapshot=_snapshot_counters(),
            queues=front._queues,
        )

    def wait_converged(self, timeout_s: float = 30.0) -> bool:
        """True once every replica's Decision sees the full mesh AND all
        replicas answer the same topology epoch — the fleet precondition
        for cross-replica bit-identical replies."""
        import time

        k = len(self.daemons)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            link_states = [
                d.decision.area_link_states.get("0") for d in self.daemons
            ]
            if all(
                ls is not None and len(ls.node_names) == k
                for ls in link_states
            ):
                epochs = {
                    d.serving.backend.epoch("0") for d in self.daemons
                }
                if len(epochs) == 1:
                    return True
            time.sleep(0.05)
        return False

    # -- elastic membership (docs/ARCHITECTURE.md "Engine snapshots &
    # elastic scale-out") --------------------------------------------------

    def scale(self, k_new: int) -> list:
        """Elastic membership under live load: grow or shrink the fleet
        to `k_new` replicas one step at a time.  Scale-out replicas are
        snapshot-warm-started from daemon 0's device engine before they
        join the router, so their first routed query finds residency and
        prewarmed programs instead of a cold build; scale-in folds the
        departed replica's final counters into the router's roll-up so
        the fleet wire surface stays monotone.  Returns the restore mode
        ("replay"/"install"/"cold"/None) per scale-out step."""
        if k_new < 1:
            raise ValueError("ServingFleet cannot scale below one replica")
        if self.router is None:
            raise RuntimeError("scale() requires a started fleet")
        modes: list = []
        while len(self.daemons) > k_new:
            self._scale_in()
        while len(self.daemons) < k_new:
            modes.append(self._scale_out())
        return modes

    def autoscale_step(self, policy) -> "object":
        """One autoscaling observation: feed the router's fleet counter
        roll-up plus the deepest replica admission queue to the policy
        (snapshot.AutoscalePolicy) and apply its decision through
        scale().  Returns the AutoscaleDecision."""
        k = len(self.daemons)
        depth = max(
            (d.serving.admission.size() for d in self.daemons), default=0
        )
        decision = policy.observe(
            k, self.router.get_counters(), admission_depth=depth
        )
        if decision.action != "hold" and decision.target_k != k:
            self.scale(decision.target_k)
        return decision

    def _scale_out(self):
        from .serving import SchedulerReplica
        from .snapshot import SNAPSHOT_COUNTERS
        from .types import LinkEvent

        donor = self.daemons[0]
        peers = list(zip(self._indices, self._names, self.daemons))
        daemon = self._new_daemon()
        idx = self._indices[-1]
        name = self._names[-1]
        daemon.start()
        # mesh the joiner with every live peer, then announce the links
        # on both sides (same choreography as start(), minted indices)
        for j, jname, _ in peers:
            self.spark_fabric.connect(
                jname, f"if-{j}-{idx}", name, f"if-{idx}-{j}"
            )
        for j, jname, peer in peers:
            peer.netlink_events_queue.push(
                LinkEvent(f"if-{j}-{idx}", idx + 1, True)
            )
            daemon.netlink_events_queue.push(
                LinkEvent(f"if-{idx}-{j}", j + 1, True)
            )
        self.wait_converged()
        mode = self._warm_start(donor, daemon)
        # join the router last: the first routed query already finds the
        # restored residency and prewarmed programs
        self.router.add_replica(SchedulerReplica(name, daemon.serving))
        SNAPSHOT_COUNTERS._bump("snapshot.scaleouts")
        return mode

    def _scale_in(self) -> None:
        from .snapshot import SNAPSHOT_COUNTERS

        if len(self.daemons) <= 1:
            raise ValueError("ServingFleet cannot scale below one replica")
        # always retire the youngest replica: daemon 0 owns the front
        # door handler and is the snapshot donor
        name = self._names[-1]
        daemon = self.daemons[-1]
        if self.router is not None:
            # stops new picks immediately and folds the replica's final
            # counters into the departed roll-up before the handle dies
            self.router.remove_replica(name)
        daemon.stop()
        self.daemons.pop()
        self._names.pop()
        self._indices.pop()
        SNAPSHOT_COUNTERS._bump("snapshot.scaleins")

    def _warm_start(self, donor: "OpenrDaemon", joiner: "OpenrDaemon"):
        """Snapshot-restore the joiner's device engine from the donor's.
        Converged fleets hit the content-equality install rung (the
        joiner's fresh mirror matches the donor's structural planes);
        drift demotes to an accounted cold build — never an error.  Hosts
        without a device backend skip silently (None)."""
        from .snapshot import EngineSnapshot

        d_spf = getattr(donor.decision.spf_solver, "spf", None)
        j_spf = getattr(joiner.decision.spf_solver, "spf", None)
        if not hasattr(d_spf, "csr_mirror") or not hasattr(
            j_spf, "csr_mirror"
        ):
            return None
        d_eng = getattr(d_spf, "engine", None)
        j_eng = getattr(j_spf, "engine", None)
        d_ls = donor.decision.area_link_states.get("0")
        j_ls = joiner.decision.area_link_states.get("0")
        if None in (d_eng, j_eng, d_ls, j_ls):
            return None
        try:
            snap = EngineSnapshot.take(d_eng, d_spf.csr_mirror(d_ls))
            return snap.restore(j_eng, j_spf.csr_mirror(j_ls))
        except Exception:  # noqa: BLE001 — warm start is best-effort
            log.exception("snapshot warm-start failed; replica joins cold")
            return None

    def stop(self) -> None:
        if self.router is not None:
            self.router.stop()
        for daemon in self.daemons:
            daemon.stop()


def build_flag_parser() -> argparse.ArgumentParser:
    """Process-level flag surface (reference: openr/common/Flags.cpp — the
    operationally-relevant subset; most knobs live in the JSON config, and
    every flag here overrides its config field, mirroring GflagConfig's
    flag->config bridge, openr/config/GflagConfig.h)."""
    parser = argparse.ArgumentParser(description="openr_tpu daemon")
    parser.add_argument("--config", required=True, help="JSON config file")
    parser.add_argument(
        "--use-device-spf",
        action="store_true",
        default=True,
        help="use the batched TPU SPF backend (default)",
    )
    parser.add_argument(
        "--no-device-spf",
        dest="use_device_spf",
        action="store_false",
        help="force the host Dijkstra SPF backend",
    )
    # identity / ports (reference: --node_name, --openr_ctrl_port,
    # --fib_port)
    parser.add_argument("--node-name", default=None)
    parser.add_argument("--listen-addr", default=None)
    parser.add_argument("--openr-ctrl-port", type=int, default=None)
    parser.add_argument("--fib-agent-host", default=None)
    parser.add_argument("--fib-agent-port", type=int, default=None)
    # drain / operation (reference: --assume_drained,
    # --override_drain_state, --dryrun, --enable_watchdog)
    parser.add_argument("--assume-drained", action="store_true", default=None)
    parser.add_argument(
        "--override-drain-state", action="store_true", default=None
    )
    parser.add_argument("--dryrun", action="store_true", default=None)
    parser.add_argument(
        "--disable-watchdog",
        dest="enable_watchdog",
        action="store_false",
        default=None,
    )
    # features (reference: --enable_flood_optimization, --is_flood_root,
    # --enable_netlink analog, --bgp_use_igp_metric plugin seam)
    parser.add_argument(
        "--enable-flood-optimization", action="store_true", default=None
    )
    parser.add_argument("--enable-netlink", action="store_true", default=None)
    parser.add_argument("--plugin-module", default=None)
    # decision timers (reference: --decision_debounce_min/max_ms)
    parser.add_argument("--decision-debounce-min-ms", type=int, default=None)
    parser.add_argument("--decision-debounce-max-ms", type=int, default=None)
    # persistent state (reference: --config_store_filepath)
    parser.add_argument("--config-store-path", default=None)
    # ctrl mTLS + peer ACL (reference: --x509_cert_path etc.)
    parser.add_argument("--tls-cert-path", default=None)
    parser.add_argument("--tls-key-path", default=None)
    parser.add_argument("--tls-ca-path", default=None)
    parser.add_argument("--tls-acl-regex", default=None)
    parser.add_argument("-v", "--verbose", action="store_true")
    return parser


def apply_flag_overrides(config, args) -> None:
    """Flag-over-config precedence (reference: GflagConfig bridge)."""
    overrides = {
        "node_name": args.node_name,
        "listen_addr": args.listen_addr,
        "openr_ctrl_port": args.openr_ctrl_port,
        "fib_agent_host": args.fib_agent_host,
        "fib_agent_port": args.fib_agent_port,
        "assume_drained": args.assume_drained,
        "override_drain_state": args.override_drain_state,
        "dryrun": args.dryrun,
        "enable_watchdog": args.enable_watchdog,
        "enable_netlink": args.enable_netlink,
        "plugin_module": args.plugin_module,
        "persistent_config_store_path": args.config_store_path,
    }
    for name, value in overrides.items():
        if value is not None:
            setattr(config, name, value)
    if (
        args.tls_cert_path
        or args.tls_key_path
        or args.tls_ca_path
        or args.tls_acl_regex
    ):
        from .config import TlsConf

        tls = config.tls_config or TlsConf()
        for cfg_field, flag in (
            ("cert_path", args.tls_cert_path),
            ("key_path", args.tls_key_path),
            ("ca_path", args.tls_ca_path),
            ("acl_regex", args.tls_acl_regex),
        ):
            if flag is not None:
                setattr(tls, cfg_field, flag)
        config.tls_config = tls
    if args.enable_flood_optimization is not None:
        config.kvstore_config.enable_flood_optimization = (
            args.enable_flood_optimization
        )
    if args.decision_debounce_min_ms is not None:
        config.decision_config.debounce_min_ms = args.decision_debounce_min_ms
    if args.decision_debounce_max_ms is not None:
        config.decision_config.debounce_max_ms = args.decision_debounce_max_ms


def main(argv: Optional[list[str]] = None) -> int:
    args = build_flag_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    config = load_config(args.config)
    apply_flag_overrides(config, args)
    config.validate()
    daemon = OpenrDaemon(config, use_device_spf=args.use_device_spf)
    daemon.start()
    log.info(
        "openr_tpu %s up; ctrl on [%s]:%d",
        config.node_name,
        config.listen_addr,
        daemon.ctrl_port,
    )
    stop_event = threading.Event()
    if threading.current_thread() is threading.main_thread():
        signal.signal(signal.SIGINT, lambda *a: stop_event.set())
        signal.signal(signal.SIGTERM, lambda *a: stop_event.set())
    stop_event.wait()
    daemon.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
