"""PrefixManager: route origination + cross-area redistribution."""

from .prefix_manager import OriginatedPrefixConfig, PrefixManager

__all__ = ["OriginatedPrefixConfig", "PrefixManager"]
