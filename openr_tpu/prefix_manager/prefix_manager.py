"""PrefixManager: owns what this node advertises into the network.

Functional equivalent of the reference's PrefixManager
(openr/prefix-manager/PrefixManager.{h,cpp}; doc
openr/docs/Protocol_Guide/PrefixManager.md):

- tracks originated prefixes per source type (LOOPBACK / BGP / RIB /
  CONFIG / ...) from the prefixUpdatesQueue (ADD / WITHDRAW /
  WITHDRAW_BY_TYPE / SYNC_BY_TYPE semantics);
- advertises ONE KvStore key per prefix
  (`prefix:[node]:[area]:[prefix]`, PrefixDatabase with exactly one
  entry) via KvStoreClientInternal.persist_key; the best entry among
  competing source types is selected by PrefixMetrics then type priority;
- withdrawal: short-TTL tombstone with `delete_prefix = True` (Decision
  processes it as a delete) and the key stops being persisted;
- cross-area redistribution: consumes Decision route updates and
  re-advertises learned routes into every *other* area with the source
  area appended to `area_stack` (loop-prevented by Decision's
  self-reflection check);
- originated prefixes (config): aggregates advertised when at least
  `minimum_supporting_routes` more-specific RIB routes exist.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Iterable, Optional

from ..decision.rib import DecisionRouteUpdate
from ..kvstore import KvStoreClientInternal
from ..runtime.eventbase import OpenrEventBase
from ..runtime.queue import QueueClosedError, RQueue
from ..serializer import dumps
from ..types import (
    PrefixDatabase,
    PrefixEntry,
    PrefixType,
    PrefixUpdateRequest,
    normalize_prefix,
    prefix_key,
)
import ipaddress

log = logging.getLogger(__name__)

WITHDRAW_TTL_MS = 10_000  # tombstone lifetime

# reference: higher type value wins ties only after metrics; the reference
# compares PrefixMetrics first (selectBestPrefixMetrics) then type
_TYPE_PRIORITY = {
    PrefixType.LOOPBACK: 10,
    PrefixType.CONFIG: 20,
    PrefixType.BREEZE: 30,
    PrefixType.PREFIX_ALLOCATOR: 40,
    PrefixType.RIB: 50,
    PrefixType.DEFAULT: 60,
    PrefixType.VIP: 70,
    PrefixType.BGP: 80,
}


@dataclass(slots=True)
class OriginatedPrefixConfig:
    """Reference: thrift::OriginatedPrefix (OpenrConfig.thrift:228)."""

    prefix: str
    minimum_supporting_routes: int = 1
    install_to_fib: bool = False
    forwarding_type: Optional[int] = None
    tags: tuple[str, ...] = ()


@dataclass(slots=True)
class OriginatedRouteState:
    config: OriginatedPrefixConfig
    supporting_routes: set[str] = field(default_factory=set)
    advertised: bool = False


class PrefixManager(OpenrEventBase):
    def __init__(
        self,
        node_name: str,
        kvstore_client: KvStoreClientInternal,
        *,
        prefix_updates: Optional[RQueue[PrefixUpdateRequest]] = None,
        route_updates: Optional[RQueue[DecisionRouteUpdate]] = None,
        areas: tuple[str, ...] = ("0",),
        originated_prefixes: Iterable[OriginatedPrefixConfig] = (),
    ) -> None:
        super().__init__(name=f"prefix-manager-{node_name}")
        self.node_name = node_name
        self.client = kvstore_client
        self._prefix_updates = prefix_updates
        self._route_updates = route_updates
        self.areas = areas
        # prefix -> type -> entry
        self.prefixes: dict[str, dict[PrefixType, PrefixEntry]] = {}
        # prefix -> set of areas currently advertised into
        self._advertised: dict[str, set[str]] = {}
        self.originated: dict[str, OriginatedRouteState] = {
            normalize_prefix(cfg.prefix): OriginatedRouteState(cfg)
            for cfg in originated_prefixes
        }
        self.counters: dict[str, int] = {}

    def _bump(self, counter: str, n: int = 1) -> None:
        self.counters[counter] = self.counters.get(counter, 0) + n

    # -- lifecycle -----------------------------------------------------------

    def run(self) -> None:
        super().run()
        self.wait_until_running()
        self.run_in_event_base_thread(self._setup).result()

    def _setup(self) -> None:
        if self._prefix_updates is not None:
            self.add_fiber_task(self._prefix_updates_fiber(), name="prefixUpdates")
        if self._route_updates is not None:
            self.add_fiber_task(self._route_updates_fiber(), name="routeUpdates")

    async def _prefix_updates_fiber(self) -> None:
        while True:
            try:
                request = await self._prefix_updates.aget()
            except QueueClosedError:
                return
            try:
                self._process_prefix_request(request)
            except Exception:
                log.exception("prefix-manager: request failed")

    async def _route_updates_fiber(self) -> None:
        while True:
            try:
                update = await self._route_updates.aget()
            except QueueClosedError:
                return
            try:
                self._process_route_update(update)
            except Exception:
                log.exception("prefix-manager: route update failed")

    # -- origination API (reference: advertisePrefixes/withdrawPrefixes) -----

    def _process_prefix_request(self, request: PrefixUpdateRequest) -> None:
        ptype = request.type
        changed: set[str] = set()
        for entry in request.prefixes_to_add:
            # fall back to each entry's own origination type when the
            # request doesn't carry one
            changed |= self._add_entry(ptype or entry.type, entry)
        for prefix in request.prefixes_to_del:
            changed |= self._del_entry(ptype, prefix)
        for prefix in changed:
            self._sync_prefix(prefix, request.dst_areas or self.areas)

    def advertise_prefixes(
        self, ptype: PrefixType, entries: list[PrefixEntry]
    ) -> None:
        def _do() -> None:
            changed: set[str] = set()
            for entry in entries:
                changed |= self._add_entry(ptype, entry)
            for prefix in changed:
                self._sync_prefix(prefix, self.areas)

        self.run_in_event_base_thread(_do).result()

    def withdraw_prefixes(self, ptype: PrefixType, prefixes: list[str]) -> None:
        def _do() -> None:
            changed: set[str] = set()
            for prefix in prefixes:
                changed |= self._del_entry(ptype, prefix)
            for prefix in changed:
                self._sync_prefix(prefix, self.areas)

        self.run_in_event_base_thread(_do).result()

    def withdraw_prefixes_by_type(self, ptype: PrefixType) -> None:
        def _do() -> None:
            changed = {
                p for p, by_type in self.prefixes.items() if ptype in by_type
            }
            for prefix in changed:
                self._del_entry(ptype, prefix)
                self._sync_prefix(prefix, self.areas)

        self.run_in_event_base_thread(_do).result()

    def sync_prefixes_by_type(
        self, ptype: PrefixType, entries: list[PrefixEntry]
    ) -> None:
        """Replace the full set for a type (reference: SYNC_PREFIXES_BY_TYPE)."""

        def _do() -> None:
            new = {normalize_prefix(e.prefix) for e in entries}
            changed: set[str] = set()
            for prefix, by_type in list(self.prefixes.items()):
                if ptype in by_type and prefix not in new:
                    changed |= self._del_entry(ptype, prefix)
            for entry in entries:
                changed |= self._add_entry(ptype, entry)
            for prefix in changed:
                self._sync_prefix(prefix, self.areas)

        self.run_in_event_base_thread(_do).result()

    def get_prefixes(self, ptype: Optional[PrefixType] = None) -> list[PrefixEntry]:
        def _get() -> list[PrefixEntry]:
            out = []
            for by_type in self.prefixes.values():
                for t, entry in by_type.items():
                    if ptype is None or t == ptype:
                        out.append(entry)
            return out

        return self.run_in_event_base_thread(_get).result()

    # -- internals -----------------------------------------------------------

    def _add_entry(self, ptype: PrefixType, entry: PrefixEntry) -> set[str]:
        prefix = normalize_prefix(entry.prefix)
        by_type = self.prefixes.setdefault(prefix, {})
        if by_type.get(ptype) == entry:
            return set()
        by_type[ptype] = entry
        self._bump("prefix_manager.advertise_requests")
        return {prefix}

    def _del_entry(self, ptype: Optional[PrefixType], prefix: str) -> set[str]:
        prefix = normalize_prefix(prefix)
        by_type = self.prefixes.get(prefix)
        if by_type is None:
            return set()
        if ptype is None:
            by_type.clear()
        elif by_type.pop(ptype, None) is None:
            return set()
        if not by_type:
            del self.prefixes[prefix]
        self._bump("prefix_manager.withdraw_requests")
        return {prefix}

    def _best_entry(self, prefix: str) -> Optional[PrefixEntry]:
        """Best among source types: PrefixMetrics then type priority
        (reference: PrefixManager.cpp:290 selectBestPrefixMetrics)."""
        by_type = self.prefixes.get(prefix)
        if not by_type:
            return None
        best_type = max(
            by_type,
            key=lambda t: (
                by_type[t].metrics.path_preference,
                by_type[t].metrics.source_preference,
                -by_type[t].metrics.distance,
                _TYPE_PRIORITY.get(t, 0),
            ),
        )
        return by_type[best_type]

    def _sync_prefix(self, prefix: str, areas: Iterable[str]) -> None:
        """(Re-)advertise or withdraw one prefix key per area.

        Any area already present in the selected entry's own `area_stack`
        is treated as a withdrawal even though the entry exists (reference:
        PrefixManager.cpp:239-247 areaStack.count(toArea)): a redistributed
        route must never be advertised back into an area it traversed, and
        if the best-path shift added an area to the stack, the previously
        advertised key there gets tombstoned rather than left stale.
        Computed per-entry so a competing self-originated entry (empty
        stack) winning best-entry selection is unaffected."""
        entry = self._best_entry(prefix)
        skip_areas = set(entry.area_stack) if entry is not None else set()
        advertised = self._advertised.setdefault(prefix, set())
        for area in areas:
            key = prefix_key(self.node_name, prefix, area)
            if entry is not None and area not in skip_areas:
                db = PrefixDatabase(
                    this_node_name=self.node_name,
                    prefix_entries=[entry],
                    area=area,
                )
                self.client.persist_key(area, key, dumps(db))
                advertised.add(area)
                self._bump("prefix_manager.advertised_keys")
            elif area in advertised:
                tombstone = PrefixDatabase(
                    this_node_name=self.node_name,
                    prefix_entries=[PrefixEntry(prefix=prefix)],
                    delete_prefix=True,
                    area=area,
                )
                self.client.clear_key(area, key, dumps(tombstone), WITHDRAW_TTL_MS)
                advertised.discard(area)
                self._bump("prefix_manager.withdrawn_keys")
        if not advertised:
            self._advertised.pop(prefix, None)

    # -- redistribution + originated prefixes (route-update consumer) --------

    def _process_route_update(self, update: DecisionRouteUpdate) -> None:
        # cross-area redistribution (reference: PrefixManager route updates
        # consumer; only meaningful with >= 2 areas)
        if len(self.areas) > 1:
            for prefix, entry in update.unicast_routes_to_update.items():
                best = entry.best_prefix_entry
                if best is None:
                    continue
                src_area = entry.best_area
                redistributed = PrefixEntry(
                    prefix=prefix,
                    type=PrefixType.RIB,
                    forwarding_type=best.forwarding_type,
                    forwarding_algorithm=best.forwarding_algorithm,
                    metrics=best.metrics,
                    tags=best.tags,
                    area_stack=tuple(best.area_stack) + (src_area,),
                    min_nexthop=best.min_nexthop,
                )
                changed = self._add_entry(PrefixType.RIB, redistributed)
                # _sync_prefix skips every area in the entry's area_stack
                # (which includes src_area, appended above)
                for p in changed:
                    self._sync_prefix(p, self.areas)
            for prefix in update.unicast_routes_to_delete:
                for p in self._del_entry(PrefixType.RIB, prefix):
                    self._sync_prefix(p, self.areas)

        # originated-prefix aggregation
        if self.originated:
            self._update_originated(update)

    def _update_originated(self, update: DecisionRouteUpdate) -> None:
        """Count supporting routes per aggregate; advertise when threshold
        met (reference: originated prefixes w/ minimum_supporting_routes)."""
        changed: set[str] = set()
        for agg, state in self.originated.items():
            agg_net = ipaddress.ip_network(agg)
            for prefix in update.unicast_routes_to_update:
                net = ipaddress.ip_network(prefix)
                if (
                    net.version == agg_net.version
                    and net.prefixlen > agg_net.prefixlen
                    and net.subnet_of(agg_net)
                ):
                    state.supporting_routes.add(prefix)
            for prefix in update.unicast_routes_to_delete:
                state.supporting_routes.discard(prefix)
            should_advertise = (
                len(state.supporting_routes)
                >= state.config.minimum_supporting_routes
            )
            if should_advertise != state.advertised:
                state.advertised = should_advertise
                if should_advertise:
                    self._add_entry(
                        PrefixType.CONFIG,
                        PrefixEntry(
                            prefix=agg,
                            type=PrefixType.CONFIG,
                            tags=state.config.tags,
                        ),
                    )
                else:
                    self._del_entry(PrefixType.CONFIG, agg)
                changed.add(agg)
        for prefix in changed:
            self._sync_prefix(prefix, self.areas)

    def get_originated_prefixes(self) -> dict[str, tuple[int, bool]]:
        """prefix -> (supporting route count, advertised)."""
        return self.run_in_event_base_thread(
            lambda: {
                p: (len(s.supporting_routes), s.advertised)
                for p, s in self.originated.items()
            }
        ).result()
