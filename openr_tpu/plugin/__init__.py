"""Plugin extension point — the seam where an external route origin (the
reference's closed-source BGP speaker) attaches to a running daemon.

Reference: openr/plugin/Plugin.h:23-32 (PluginArgs{prefixUpdatesQueue,
staticRoutesUpdateQueue, routeUpdatesQueue reader, config, ssl}) with the
call site openr/Main.cpp:501-510 — started before Decision so the plugin's
origins are present for the first SPF run.

A plugin is any importable module (config.plugin_module) exposing:

    def plugin_start(args: PluginArgs) -> Any: ...
    def plugin_stop(handle: Any) -> None: ...   # optional

`plugin_start` may return a handle (threads, modules, state); the daemon
passes it back to `plugin_stop` at teardown.  Through the args a plugin
can originate prefixes (PrefixUpdateRequest -> PrefixManager), inject
static routes (DecisionRouteUpdate -> Decision/Fib overlay), and observe
every computed route delta (route_updates reader) — the full BGP-speaker
contract.  See examples/route_injector_plugin.py.
"""

from __future__ import annotations

import importlib
from dataclasses import dataclass
from typing import Any, Optional

from ..runtime.queue import ReplicateQueue, RQueue


@dataclass
class PluginArgs:
    """Reference: struct PluginArgs (Plugin.h:23-30)."""

    prefix_updates_queue: ReplicateQueue  # write PrefixUpdateRequest
    static_routes_update_queue: ReplicateQueue  # write DecisionRouteUpdate
    route_updates_queue: RQueue  # read DecisionRouteUpdate deltas
    config: Any  # OpenrConfig
    node_name: str = ""


def load_plugin(module_name: str):
    """Resolve a plugin module by import path; raises ImportError with the
    module name in the message (a bad plugin_module config should fail the
    daemon loudly, mirroring the reference's link-time binding)."""
    module = importlib.import_module(module_name)
    if not callable(getattr(module, "plugin_start", None)):
        raise ImportError(
            f"plugin module {module_name!r} has no plugin_start(args)"
        )
    return module


def plugin_start(module, args: PluginArgs) -> Any:
    return module.plugin_start(args)


def plugin_stop(module, handle: Any) -> None:
    stop = getattr(module, "plugin_stop", None)
    if callable(stop):
        stop(handle)
