"""Engine snapshots: replica warm-start, delta-replay restore, and the
elastic-fleet autoscaling policy (see snapshot.py's module docstring for
the restore-rung contract)."""

from .autoscale import AutoscaleDecision, AutoscalePolicy
from .snapshot import (
    SNAPSHOT_COUNTER_KEYS,
    SNAPSHOT_COUNTERS,
    SNAPSHOT_VERSION,
    EngineSnapshot,
    SnapshotCounters,
    SnapshotFormatError,
)

__all__ = [
    "AutoscaleDecision",
    "AutoscalePolicy",
    "EngineSnapshot",
    "SnapshotCounters",
    "SnapshotFormatError",
    "SNAPSHOT_COUNTER_KEYS",
    "SNAPSHOT_COUNTERS",
    "SNAPSHOT_VERSION",
]
