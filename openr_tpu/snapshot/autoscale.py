"""Counter-driven elastic autoscaling for the serving fleet.

The policy is deliberately simple and deterministic: `observe()` is a
pure function of the counter deltas since the previous observation plus
the current admission-queue depth, so it unit-tests without a fleet and
never introduces schedule nondeterminism of its own.  It reads only
surfaces the fleet already exports — the router's ``serving.router.*``
family and the admission RWQueue depth — and returns a decision; the
caller (``ServingFleet.autoscale_step``) applies it through
``ServingFleet.scale``, which owns the snapshot warm-start and the
``snapshot.scaleouts`` / ``snapshot.scaleins`` accounting.

Scale-out pressure is either signal of saturation: router sheds since
the last observation (queries that never got a first dispatch), or the
admission queue standing deeper than ``depth_high``.  Scale-in needs
``idle_intervals`` consecutive quiet observations (dispatch delta at or
below ``idle_dispatches``) — a single quiet tick is noise, not idleness.
Every scaling action arms a ``cooldown`` of observations so the policy
never flaps faster than replicas can join or leave.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class AutoscaleDecision:
    action: str  # "scale_out" | "scale_in" | "hold"
    target_k: int
    reason: str


class AutoscalePolicy:
    def __init__(
        self,
        *,
        min_replicas: int = 1,
        max_replicas: int = 4,
        shed_high: int = 1,
        depth_high: int = 64,
        idle_dispatches: int = 0,
        idle_intervals: int = 3,
        cooldown: int = 2,
    ) -> None:
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.shed_high = int(shed_high)
        self.depth_high = int(depth_high)
        self.idle_dispatches = int(idle_dispatches)
        self.idle_intervals = int(idle_intervals)
        self.cooldown = int(cooldown)
        self._last_sheds = 0
        self._last_dispatches = 0
        self._idle_streak = 0
        self._cooldown_left = 0

    def observe(
        self, k: int, counters: dict, admission_depth: int = 0
    ) -> AutoscaleDecision:
        """One policy tick over a `ReplicaRouter.get_counters()` snapshot
        (cumulative — the policy differences it internally) and the
        current admission-queue depth."""
        sheds = int(counters.get("serving.router.sheds", 0))
        dispatches = int(counters.get("serving.router.dispatches", 0))
        d_sheds = sheds - self._last_sheds
        d_dispatches = dispatches - self._last_dispatches
        self._last_sheds = sheds
        self._last_dispatches = dispatches

        if self._cooldown_left > 0:
            self._cooldown_left -= 1
            return AutoscaleDecision("hold", k, "cooldown")

        pressed = d_sheds >= self.shed_high or (
            admission_depth >= self.depth_high
        )
        if pressed:
            self._idle_streak = 0
            if k < self.max_replicas:
                self._cooldown_left = self.cooldown
                why = (
                    f"sheds+{d_sheds}"
                    if d_sheds >= self.shed_high
                    else f"admission_depth={admission_depth}"
                )
                return AutoscaleDecision("scale_out", k + 1, why)
            return AutoscaleDecision("hold", k, "at max_replicas")

        if d_dispatches <= self.idle_dispatches:
            self._idle_streak += 1
            if self._idle_streak >= self.idle_intervals:
                self._idle_streak = 0
                if k > self.min_replicas:
                    self._cooldown_left = self.cooldown
                    return AutoscaleDecision(
                        "scale_in", k - 1, "idle intervals"
                    )
                return AutoscaleDecision("hold", k, "at min_replicas")
            return AutoscaleDecision("hold", k, "idle, streak building")

        self._idle_streak = 0
        return AutoscaleDecision("hold", k, "steady")
