"""Engine snapshots: checkpoint a residency engine, warm-start another.

A new replica today pays the full cold path — CSR build, device upload,
AOT-ladder compiles — before it answers a single query.  `EngineSnapshot`
moves that cost off the serving path: it checkpoints a
`DeviceResidencyEngine`'s resident graph arrays (host-side mirror, in a
versioned serial format with an integrity digest), the rewire-log
position, the LinkState epoch, and a program-cache *manifest* — ladder
keys only, never executables: programs recompile lazily or pre-warm from
the manifest through `engine.prewarm`, which lowers against
ShapeDtypeStructs so no example arrays are ever materialized.

Restore rungs (`EngineSnapshot.restore`, in preference order):

- **replay** — the target is the donor mirror itself (same CsrTopology
  object, same ELL identity — a rebuild replaces the ELL object, so the
  identity pin cannot survive one).  The resident is installed at the
  snapshot's (epoch, rewire_seq) position and `engine.sync()` replays
  the rewire/delta chain since the snapshot epoch through the engine's
  existing ladder.  A chain gap demotes *inside* sync() — accounted as
  `device.engine.rewire_fallbacks` plus `snapshot.replay_fallbacks`,
  never an error.
- **install** — a foreign mirror (fresh replica) whose full content is
  identical to the checkpoint: direct install, adopting the target's
  (version, rewire_seq) lineage.  No replay needed; bit-exact by
  construction.
- **cold** — anything else (stale snapshot against a drifted foreign
  mirror, structural mismatch): accounted demotion to a full restage
  (`snapshot.replay_fallbacks`), never an error.

Every restore leaves `csr` fully resident and answering bit-exact
against a cold build of the same LinkState — the demotion rule trades
only the warm-start saving, never correctness.

The `snapshot.*` counter family is pre-seeded the way the engine and
fuzzer registries are: the `SNAPSHOT_COUNTERS` singleton is wired as
the ctrl handler's ``snapshot`` module, so the whole family answers one
getCounters on both wire surfaces (native ctrl + fb303 shim) before any
snapshot is ever taken.
"""

from __future__ import annotations

import hashlib
import json
import struct
import time
from dataclasses import dataclass
from typing import Optional

import jax
import numpy as np

from ..obs import trace as _trace

# serialized-format version: any layout change bumps it; `from_bytes`
# refuses a mismatched artifact (SnapshotFormatError), it never guesses
SNAPSHOT_VERSION = 1
_MAGIC = b"OTPUSNAP"

SNAPSHOT_COUNTER_KEYS = (
    "snapshot.taken",
    "snapshot.take_us",
    "snapshot.bytes",
    "snapshot.restores",
    "snapshot.restore_us",
    "snapshot.replayed_events",
    "snapshot.replay_fallbacks",
    "snapshot.digest_failures",
    "snapshot.manifest_programs",
    "snapshot.prewarmed_programs",
    "snapshot.scaleouts",
    "snapshot.scaleins",
)


class SnapshotCounters:
    """Pre-seeded ``snapshot.*`` registry (the engine/fuzzer pattern)."""

    def __init__(self) -> None:
        self.counters: dict[str, int] = {k: 0 for k in SNAPSHOT_COUNTER_KEYS}

    def get_counters(self) -> dict[str, int]:
        return dict(self.counters)

    def _bump(self, name: str, delta: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + delta


SNAPSHOT_COUNTERS = SnapshotCounters()


class SnapshotFormatError(RuntimeError):
    """Corrupt or incompatible serialized snapshot (bad magic, format
    version skew, integrity-digest mismatch).  Deliberately NOT the
    restore demotion path: a damaged artifact is an error, a
    stale-but-intact snapshot demotes to a cold build."""


# the engine-resident arrays a checkpoint carries, in serial order
_ARRAY_FIELDS = (
    "edge_src",
    "edge_dst",
    "edge_metric",
    "edge_up",
    "node_overloaded",
    "out_slot",
)


@dataclass
class EngineSnapshot:
    """One residency checkpoint: host arrays + position + manifest.

    Built by `take` (live engine) or `from_bytes` (serialized artifact).
    The two lineage pins below are same-process only and never
    serialized: a deserialized snapshot can only restore through the
    content-equality or cold rungs."""

    epoch: int  # csr.version the checkpoint was taken at
    rewire_seq: int  # rewire-log position at the checkpoint
    topo_key: tuple  # (node_capacity, edge_capacity)
    node_names: tuple
    sweep_hint: int
    arrays: dict  # name -> host np.ndarray, _ARRAY_FIELDS
    ell_leaves: list  # host np.ndarray leaves of the donor's ELL pytree
    manifest: tuple  # program-cache ladder keys for topo_key
    donor_csr_id: Optional[int] = None
    donor_ell_ref: object = None

    # -- checkpoint ---------------------------------------------------------

    @classmethod
    def take(cls, engine, csr) -> "EngineSnapshot":
        """Checkpoint `csr`'s residency on `engine` (syncing it first, so
        the snapshot is at the mirror's current version)."""
        t0 = time.perf_counter()
        tr = _trace.TRACE
        with _trace.maybe_child("engine.snapshot", op="take"):
            state = engine.export_resident(csr)
            topo_key = tuple(state["topo_key"])
            manifest = tuple(
                k for k in engine.cached_program_keys() if k[0] == topo_key
            )
            snap = cls(
                epoch=int(state["version"]),
                rewire_seq=int(state["rewire_seq"]),
                topo_key=topo_key,
                node_names=tuple(csr.node_names),
                sweep_hint=int(state["sweep_hint"]),
                arrays=state["arrays"],
                ell_leaves=state["ell_leaves"],
                manifest=manifest,
                donor_csr_id=id(csr),
                donor_ell_ref=csr.ell,
            )
            nbytes = snap.nbytes()
            SNAPSHOT_COUNTERS._bump("snapshot.taken")
            SNAPSHOT_COUNTERS._bump("snapshot.bytes", nbytes)
            SNAPSHOT_COUNTERS._bump(
                "snapshot.manifest_programs", len(manifest)
            )
            if tr is not None:
                tr.note("snapshot.bytes", nbytes)
                tr.note("snapshot.epoch", snap.epoch)
        SNAPSHOT_COUNTERS._bump(
            "snapshot.take_us", int((time.perf_counter() - t0) * 1e6)
        )
        return snap

    def nbytes(self) -> int:
        return sum(a.nbytes for a in self.arrays.values()) + sum(
            a.nbytes for a in self.ell_leaves
        )

    # -- serial format ------------------------------------------------------

    @staticmethod
    def _key_json(k: tuple) -> list:
        topo, s_bucket, n_words, n_sweeps, small, use_link_metric = k
        return [
            [int(x) for x in topo],
            int(s_bucket),
            int(n_words),
            int(n_sweeps),
            bool(small),
            bool(use_link_metric),
        ]

    @staticmethod
    def _key_from_json(k: list) -> tuple:
        return (
            tuple(int(x) for x in k[0]),
            int(k[1]),
            int(k[2]),
            int(k[3]),
            bool(k[4]),
            bool(k[5]),
        )

    def _tensor_list(self) -> tuple:
        """(metadata list, concatenated payload) in serial order."""
        metas: list = []
        chunks: list = []
        for name in _ARRAY_FIELDS:
            a = np.ascontiguousarray(self.arrays[name])
            metas.append(
                {"name": name, "dtype": str(a.dtype), "shape": list(a.shape)}
            )
            chunks.append(a.tobytes())
        for a in self.ell_leaves:
            a = np.ascontiguousarray(np.asarray(a))
            metas.append(
                {"name": "ell", "dtype": str(a.dtype), "shape": list(a.shape)}
            )
            chunks.append(a.tobytes())
        return metas, b"".join(chunks)

    def to_bytes(self) -> bytes:
        """MAGIC + u32 header length + JSON header + raw array payload.
        The sha256 digest covers the digest-less header and the payload,
        so bit rot anywhere in the artifact is caught at load."""
        metas, payload = self._tensor_list()
        header = {
            "format": SNAPSHOT_VERSION,
            "epoch": int(self.epoch),
            "rewire_seq": int(self.rewire_seq),
            "topo_key": [int(x) for x in self.topo_key],
            "node_names": list(self.node_names),
            "sweep_hint": int(self.sweep_hint),
            "manifest": [self._key_json(k) for k in self.manifest],
            "tensors": metas,
        }
        digest = hashlib.sha256(
            json.dumps(header, sort_keys=True).encode() + payload
        ).hexdigest()
        header["digest"] = digest
        hdr = json.dumps(header, sort_keys=True).encode()
        return _MAGIC + struct.pack("<I", len(hdr)) + hdr + payload

    @classmethod
    def from_bytes(cls, blob: bytes) -> "EngineSnapshot":
        if blob[: len(_MAGIC)] != _MAGIC:
            raise SnapshotFormatError("bad snapshot magic")
        off = len(_MAGIC)
        if len(blob) < off + 4:
            raise SnapshotFormatError("truncated snapshot header")
        (hlen,) = struct.unpack_from("<I", blob, off)
        off += 4
        try:
            header = json.loads(blob[off : off + hlen].decode())
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise SnapshotFormatError(f"unreadable snapshot header: {e}")
        off += hlen
        fmt = int(header.get("format", -1))
        if fmt != SNAPSHOT_VERSION:
            raise SnapshotFormatError(
                f"snapshot format {fmt} != {SNAPSHOT_VERSION}; "
                "retake the snapshot with the current writer"
            )
        payload = blob[off:]
        digest = header.pop("digest", "")
        expect = hashlib.sha256(
            json.dumps(header, sort_keys=True).encode() + payload
        ).hexdigest()
        if digest != expect:
            SNAPSHOT_COUNTERS._bump("snapshot.digest_failures")
            raise SnapshotFormatError("snapshot integrity digest mismatch")
        arrays: dict = {}
        leaves: list = []
        pos = 0
        for meta in header["tensors"]:
            dtype = np.dtype(meta["dtype"])
            count = int(np.prod(meta["shape"], dtype=np.int64))
            a = (
                np.frombuffer(payload, dtype=dtype, count=count, offset=pos)
                .reshape(meta["shape"])
                .copy()
            )
            pos += a.nbytes
            if meta["name"] == "ell":
                leaves.append(a)
            else:
                arrays[meta["name"]] = a
        return cls(
            epoch=int(header["epoch"]),
            rewire_seq=int(header["rewire_seq"]),
            topo_key=tuple(int(x) for x in header["topo_key"]),
            node_names=tuple(header["node_names"]),
            sweep_hint=int(header["sweep_hint"]),
            arrays=arrays,
            ell_leaves=leaves,
            manifest=tuple(
                cls._key_from_json(k) for k in header["manifest"]
            ),
        )

    # -- restore ------------------------------------------------------------

    def _structure_matches(self, csr) -> bool:
        """Shapes line up: capacities and the ELL pytree leaf layout."""
        if tuple(self.topo_key) != (csr.node_capacity, csr.edge_capacity):
            return False
        target = jax.tree_util.tree_leaves(csr.ell)
        if len(target) != len(self.ell_leaves):
            return False
        for mine, theirs in zip(self.ell_leaves, target):
            t = np.asarray(theirs)
            if mine.shape != t.shape or mine.dtype != t.dtype:
                return False
        return True

    def _content_matches(self, csr) -> bool:
        """Content equality against a foreign mirror: same node
        ordering, same edge-slot encoding and attributes, same ELL
        structure.  The ELL's `w`/`ok`/`transit_ok` planes are derived —
        every consumer recomputes them from edge_metric / edge_up /
        node_overloaded (compared above) at relax time, and they
        legitimately go stale on the donor across in-place attribute
        refreshes — so only `nbr`/`edge_id` and the relabeling maps are
        compared.  Holds whenever the target was built deterministically
        from the same LinkState the donor last rebuilt at (the fleet
        scale-out case); any real drift demotes to cold instead."""
        if tuple(self.node_names) != tuple(csr.node_names):
            return False
        for name in _ARRAY_FIELDS:
            if not np.array_equal(self.arrays[name], getattr(csr, name)):
                return False
        theirs = csr.ell
        mine = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(theirs), self.ell_leaves
        )
        for bm, bt in zip(mine.buckets, theirs.buckets):
            if not np.array_equal(bm.nbr, np.asarray(bt.nbr)):
                return False
            if not np.array_equal(bm.edge_id, np.asarray(bt.edge_id)):
                return False
        return np.array_equal(
            mine.new_of_old, np.asarray(theirs.new_of_old)
        ) and np.array_equal(mine.old_of_new, np.asarray(theirs.old_of_new))

    def _state(self) -> dict:
        return {
            "topo_key": self.topo_key,
            "version": self.epoch,
            "rewire_seq": self.rewire_seq,
            "sweep_hint": self.sweep_hint,
            "arrays": self.arrays,
            "ell_leaves": self.ell_leaves,
        }

    def restore(self, engine, csr, *, prewarm: bool = True) -> str:
        """Restore this checkpoint as `csr`'s residency on `engine` and
        (optionally) pre-warm the program-cache manifest.  Returns the
        rung taken: "replay" / "install" / "cold" (module docstring).
        Never raises on staleness — demotion is accounted, not fatal."""
        t0 = time.perf_counter()
        tr = _trace.TRACE
        SNAPSHOT_COUNTERS._bump("snapshot.restores")
        with _trace.maybe_child("engine.snapshot", op="restore"):
            mode = self._restore_residency(engine, csr)
            if tr is not None:
                tr.annotate("snapshot.rung", mode)
                tr.note("snapshot.epoch", self.epoch)
            if prewarm and self.manifest:
                with _trace.maybe_child("engine.snapshot.prewarm"):
                    warmed = engine.prewarm(csr, self.manifest)
                SNAPSHOT_COUNTERS._bump(
                    "snapshot.prewarmed_programs", warmed
                )
        SNAPSHOT_COUNTERS._bump(
            "snapshot.restore_us", int((time.perf_counter() - t0) * 1e6)
        )
        return mode

    def _restore_residency(self, engine, csr) -> str:
        if self._structure_matches(csr):
            if (
                self.donor_csr_id == id(csr)
                and self.donor_ell_ref is csr.ell
                and int(getattr(csr, "rewire_seq", 0)) >= self.rewire_seq
                and int(csr.version) >= self.epoch
            ):
                # donor mirror: install at the snapshot position, then
                # the engine's own ladder replays the rewire tail plus
                # any attribute drift since the checkpoint.  A chain gap
                # (log eviction past REWIRE_LOG_DEPTH) demotes inside
                # sync() — visible here as a full_restages increment.
                engine.install_resident(csr, self._state())
                c0 = engine.get_counters()
                engine.sync(csr)
                c1 = engine.get_counters()
                if (
                    c1["device.engine.full_restages"]
                    > c0["device.engine.full_restages"]
                ):
                    SNAPSHOT_COUNTERS._bump("snapshot.replay_fallbacks")
                    return "cold"
                replayed = (
                    c1["device.engine.rewires"]
                    - c0["device.engine.rewires"]
                    + c1["device.engine.incremental_updates"]
                    - c0["device.engine.incremental_updates"]
                )
                SNAPSHOT_COUNTERS._bump(
                    "snapshot.replayed_events", replayed
                )
                return "replay"
            if self._content_matches(csr):
                # content-identical foreign mirror: adopt its lineage so
                # the next sync() sees a current resident
                engine.install_resident(
                    csr,
                    self._state(),
                    version=int(csr.version),
                    rewire_seq=int(getattr(csr, "rewire_seq", 0)),
                )
                return "install"
        # stale or structurally foreign: accounted demotion, never an
        # error — the cold build is the engine's ordinary restage
        SNAPSHOT_COUNTERS._bump("snapshot.replay_fallbacks")
        engine.drop(csr)
        engine.sync(csr)
        return "cold"
