"""RangeAllocator: distributed unique-integer election via KvStore.

Functional equivalent of the reference's RangeAllocator
(openr/allocators/RangeAllocator.h:28; doc
openr/docs/Protocol_Guide/RangeAllocator.md): each node proposes a value
in [start, end] by writing the key `<keyPrefix><value>` with its own node
name as the value; the KvStore CRDT merge resolves collisions
deterministically (higher version, then originator, then value bytes).  A
node that loses its claim picks another value and retries.  Convergence:
eventually every node owns a distinct value.
"""

from __future__ import annotations

import hashlib
import logging
from typing import Callable, Optional

from ..kvstore import KvStoreClientInternal
from ..runtime.eventbase import OpenrEventBase

log = logging.getLogger(__name__)

# settle time before declaring victory (reference: kRangeAllocTtl backoff)
SETTLE_TIME_S = 0.2


class RangeAllocator:
    """Runs on the caller's event base (like the reference, which runs on
    the owning module's evb)."""

    def __init__(
        self,
        evb: OpenrEventBase,
        client: KvStoreClientInternal,
        area: str,
        key_prefix: str,
        node_name: str,
        callback: Callable[[Optional[int]], None],
        allocate_range: tuple[int, int],
        *,
        override_owner: bool = True,
        settle_time_s: float = SETTLE_TIME_S,
    ) -> None:
        self.evb = evb
        self.client = client
        self.area = area
        self.key_prefix = key_prefix
        self.node_name = node_name
        self.callback = callback
        self.start, self.end = allocate_range
        assert self.start <= self.end
        self.override_owner = override_owner
        self._settle_time_s = settle_time_s
        self.my_value: Optional[int] = None
        self._proposed: Optional[int] = None
        self._settle_timer = None
        self._stopped = False
        client.subscribe_key_filter(
            f"^{key_prefix}", self._on_key_update
        )

    def _key(self, value: int) -> str:
        return f"{self.key_prefix}{value}"

    # -- allocation ----------------------------------------------------------

    def start_allocation(self, init_value: Optional[int] = None) -> None:
        self.evb.run_in_event_base_thread(
            lambda: self._propose(init_value)
        ).result()

    def _initial_value(self) -> int:
        span = self.end - self.start + 1
        digest = int.from_bytes(
            hashlib.blake2b(self.node_name.encode(), digest_size=8).digest(),
            "big",
        )
        return self.start + digest % span

    def _propose(self, init_value: Optional[int] = None) -> None:
        if self._stopped:
            return
        value = init_value if init_value is not None else self._initial_value()
        value = max(self.start, min(self.end, value))
        # skip values already owned by a live competitor
        span = self.end - self.start + 1
        for _ in range(span):
            existing = self.client.get_key(self.area, self._key(value))
            if existing is None or existing.value in (
                None,
                self.node_name.encode(),
            ):
                break
            if self.override_owner and self.node_name.encode() > existing.value:
                break  # we'd win the CRDT tie-break; claim it
            value = self.start + (value - self.start + 1) % span
        self._proposed = value
        self.my_value = None
        log.debug("range-alloc %s: proposing %d", self.node_name, value)
        self.client.persist_key(
            self.area, self._key(value), self.node_name.encode()
        )
        self._arm_settle_timer()

    def _arm_settle_timer(self) -> None:
        if self._settle_timer is not None:
            self._settle_timer.cancel()
        self._settle_timer = self.evb.schedule_timeout(
            self._settle_time_s, self._check_victory
        )

    def _check_victory(self) -> None:
        self._settle_timer = None
        if self._stopped or self._proposed is None:
            return
        existing = self.client.get_key(self.area, self._key(self._proposed))
        if existing is not None and existing.value == self.node_name.encode():
            if self.my_value != self._proposed:
                self.my_value = self._proposed
                self.callback(self.my_value)
        else:
            self._lost(self._proposed)

    def _on_key_update(self, key: str, value) -> None:
        """Conflict detection: somebody else claimed our key."""
        if self._stopped or self._proposed is None:
            return
        if key != self._key(self._proposed):
            return
        if value is None or value.value is None:
            return
        if value.value != self.node_name.encode():
            # persist_key auto-reasserts ownership (version bump); but if
            # we do NOT override, concede and move on
            if not self.override_owner or value.value > self.node_name.encode():
                self._lost(self._proposed, concede=True)
            else:
                self._arm_settle_timer()

    def _lost(self, value: int, concede: bool = False) -> None:
        log.debug(
            "range-alloc %s: lost %d%s",
            self.node_name,
            value,
            " (conceding)" if concede else "",
        )
        self.client.unset_key(self.area, self._key(value))
        had_value = self.my_value is not None
        self.my_value = None
        if had_value:
            self.callback(None)
        span = self.end - self.start + 1
        next_value = self.start + (value - self.start + 1) % span
        self._propose(next_value)

    def stop(self) -> None:
        self._stopped = True
        if self._settle_timer is not None:
            self._settle_timer.cancel()
            self._settle_timer = None
