"""PrefixAllocator: distributed unique-subprefix election.

Functional equivalent of the reference's PrefixAllocator
(openr/allocators/PrefixAllocator.h:35; doc
openr/docs/Protocol_Guide/PrefixAllocator.md): given a seed prefix P/N and
an allocation length M, elect a unique index i in [0, 2^(M-N)) via
RangeAllocator, map it to the i-th M-length subprefix of P, advertise it
through PrefixManager (PREFIX_ALLOCATOR type), and persist the allocated
index in the config store so restarts re-propose the same value.
"""

from __future__ import annotations

import ipaddress
import logging
from typing import Optional

from ..config_store import PersistentStore
from ..kvstore import KvStoreClientInternal
from ..runtime.eventbase import OpenrEventBase
from ..types import PrefixEntry, PrefixType, PrefixUpdateRequest
from ..runtime.queue import ReplicateQueue
from .range_allocator import RangeAllocator

log = logging.getLogger(__name__)

ALLOC_PREFIX_MARKER = "allocprefix:"  # reference: Constants::kPrefixAllocMarker
CONFIG_KEY = "prefix-allocator-config"  # persisted index


class PrefixAllocator:
    def __init__(
        self,
        evb: OpenrEventBase,
        node_name: str,
        client: KvStoreClientInternal,
        seed_prefix: str,
        alloc_prefix_len: int,
        *,
        area: str = "0",
        prefix_updates_queue: Optional[ReplicateQueue[PrefixUpdateRequest]] = None,
        config_store: Optional[PersistentStore] = None,
        assign_to_interface: str = "",
    ) -> None:
        self.evb = evb
        self.node_name = node_name
        self.client = client
        self.seed = ipaddress.ip_network(seed_prefix)
        self.alloc_len = alloc_prefix_len
        assert alloc_prefix_len > self.seed.prefixlen, "alloc len must be longer"
        n_prefixes = 1 << (alloc_prefix_len - self.seed.prefixlen)
        self._prefix_updates_queue = prefix_updates_queue
        self.config_store = config_store
        self.assign_to_interface = assign_to_interface
        self._nl = None  # cached NetlinkProtocolSocket (lazy)
        import threading

        self._addr_sync_lock = threading.Lock()
        # latest-wins mailbox for the single sync worker (see
        # _sync_iface_addr); a 1-tuple so pending None is distinguishable
        self._addr_pending: Optional[tuple] = None
        self._addr_worker_busy = False
        self._addr_stopped = False
        self.my_prefix: Optional[str] = None
        self.range_allocator = RangeAllocator(
            evb,
            client,
            area,
            ALLOC_PREFIX_MARKER,
            node_name,
            self._on_allocated,
            (0, n_prefixes - 1),
        )

    def start(self) -> None:
        init = None
        if self.config_store is not None:
            raw = self.config_store.load(CONFIG_KEY)
            if raw is not None:
                try:
                    init = int(raw.decode())
                except ValueError:
                    init = None
        self.range_allocator.start_allocation(init)

    def _index_to_prefix(self, index: int) -> str:
        # i-th subprefix computed arithmetically (2^k subnets never
        # materialized)
        shift = self.seed.network_address.max_prefixlen - self.alloc_len
        base = int(self.seed.network_address) + (index << shift)
        return str(ipaddress.ip_network((base, self.alloc_len)))

    def _sync_iface_addr(self, prefix: Optional[str]) -> None:
        """Program the elected prefix's first host address onto the
        configured interface, removing every OTHER address within the
        seed prefix (reference: PrefixAllocator syncIfaceAddrs — assigns
        the allocation to the loopback and reconciles stale addresses a
        previous process instance may have left behind).  Runs the
        blocking netlink I/O on a worker thread: the allocator's
        callbacks fire on the LinkMonitor event base, which must not
        stall on kernel round-trips.  Best-effort: needs CAP_NET_ADMIN;
        failures are logged, the allocation itself is unaffected."""
        if not self.assign_to_interface:
            return
        new_addr = None
        if prefix is not None:
            net = ipaddress.ip_network(prefix)
            # first host address — except at maximum length, where +1
            # would land in the NEXT node's allocation (reference adds
            # +1 only below full length)
            host = (
                net.network_address
                if net.prefixlen == net.network_address.max_prefixlen
                else net.network_address + 1
            )
            new_addr = f"{host}/{net.prefixlen}"
        import threading

        with self._addr_sync_lock:
            if self._addr_stopped:
                return
            # latest wins: a superseded request must never be applied
            # AFTER its successor (thread-per-call could reorder)
            self._addr_pending = (new_addr,)
            if self._addr_worker_busy:
                return  # the running worker drains the mailbox
            self._addr_worker_busy = True
        threading.Thread(
            target=self._addr_sync_worker,
            name="prefix-alloc-addr-sync",
            daemon=True,
        ).start()

    def _addr_sync_worker(self) -> None:
        """Single drainer: applies the LATEST pending address; the
        netlink socket is touched only here and released on exit when
        stop() raced us."""
        while True:
            with self._addr_sync_lock:
                if self._addr_pending is None or self._addr_stopped:
                    self._addr_worker_busy = False
                    if self._addr_stopped and self._nl is not None:
                        self._nl.close_request_socket()
                        self._nl = None
                    return
                (new_addr,) = self._addr_pending
                self._addr_pending = None
            try:
                self._apply_iface_addr(new_addr)
            except Exception:
                # the worker must survive ANY failure: dying here would
                # strand _addr_worker_busy=True and wedge every future
                # sync (and stop() would never reclaim the socket)
                log.exception("prefix-allocator: address sync failed")

    def _apply_iface_addr(self, new_addr: Optional[str]) -> None:
        # no same-value short-circuit: every sync reconciles against the
        # KERNEL's actual state, so a flapped interface (link down/up
        # flushes addresses) or operator deletion self-heals on the next
        # allocator callback
        try:
            if self._nl is None:
                from ..nl.netlink import NetlinkProtocolSocket

                # one cached socket: per-sync construction would leak
                # the persistent request fd to GC under churn.  Bare write
                # is single-drainer-confined: _apply_iface_addr runs only
                # on the one live worker (the _addr_worker_busy handshake
                # under _addr_sync_lock serializes successive workers, and
                # stop()'s locked reclaim at the loop head sees the update
                # through that same lock).
                self._nl = NetlinkProtocolSocket()  # openr: disable=guarded-by
            nl = self._nl
            if_index = {
                l.if_name: l.if_index for l in nl.get_all_links()
            }.get(self.assign_to_interface)
            if if_index is None:
                log.warning(
                    "prefix-allocator: interface %s not found; "
                    "skipping address assignment",
                    self.assign_to_interface,
                )
                return
            # reconcile: every address on the interface inside the SEED
            # prefix that is not the current allocation goes — incl.
            # leftovers from a previous process instance
            for addr in nl.get_all_addresses():
                if addr.if_index != if_index:
                    continue
                try:
                    ip = ipaddress.ip_interface(addr.prefix).ip
                except ValueError:
                    continue
                if ip in self.seed and addr.prefix != new_addr:
                    try:
                        nl.del_addr(if_index, addr.prefix)
                    except OSError:
                        pass  # already gone
            if new_addr is not None:
                nl.add_addr(if_index, new_addr)
        except OSError as exc:
            log.warning(
                "prefix-allocator: address sync on %s failed: %s",
                self.assign_to_interface,
                exc,
            )

    def _on_allocated(self, index: Optional[int]) -> None:
        if index is None:
            # lost allocation: withdraw
            if self.my_prefix is not None and self._prefix_updates_queue is not None:
                self._prefix_updates_queue.push(
                    PrefixUpdateRequest(
                        prefixes_to_del=[self.my_prefix],
                        type=PrefixType.PREFIX_ALLOCATOR,
                    )
                )
            self.my_prefix = None
            self._sync_iface_addr(None)
            return
        self.my_prefix = self._index_to_prefix(index)
        log.info(
            "prefix-allocator %s: allocated index %d -> %s",
            self.node_name,
            index,
            self.my_prefix,
        )
        if self.config_store is not None:
            self.config_store.store(CONFIG_KEY, str(index).encode())
        self._sync_iface_addr(self.my_prefix)
        if self._prefix_updates_queue is not None:
            self._prefix_updates_queue.push(
                PrefixUpdateRequest(
                    prefixes_to_add=[
                        PrefixEntry(
                            prefix=self.my_prefix,
                            type=PrefixType.PREFIX_ALLOCATOR,
                        )
                    ],
                    type=PrefixType.PREFIX_ALLOCATOR,
                )
            )

    def get_my_prefix(self) -> Optional[str]:
        return self.my_prefix

    def stop(self) -> None:
        self.range_allocator.stop()
        with self._addr_sync_lock:
            self._addr_stopped = True
            self._addr_pending = None
            # a busy worker owns the socket and closes it on exit; only
            # reclaim it here when no worker is running
            if not self._addr_worker_busy and self._nl is not None:
                self._nl.close_request_socket()
                self._nl = None
