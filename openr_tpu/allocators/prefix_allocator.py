"""PrefixAllocator: distributed unique-subprefix election.

Functional equivalent of the reference's PrefixAllocator
(openr/allocators/PrefixAllocator.h:35; doc
openr/docs/Protocol_Guide/PrefixAllocator.md): given a seed prefix P/N and
an allocation length M, elect a unique index i in [0, 2^(M-N)) via
RangeAllocator, map it to the i-th M-length subprefix of P, advertise it
through PrefixManager (PREFIX_ALLOCATOR type), and persist the allocated
index in the config store so restarts re-propose the same value.
"""

from __future__ import annotations

import ipaddress
import logging
from typing import Optional

from ..config_store import PersistentStore
from ..kvstore import KvStoreClientInternal
from ..runtime.eventbase import OpenrEventBase
from ..types import PrefixEntry, PrefixType, PrefixUpdateRequest
from ..runtime.queue import ReplicateQueue
from .range_allocator import RangeAllocator

log = logging.getLogger(__name__)

ALLOC_PREFIX_MARKER = "allocprefix:"  # reference: Constants::kPrefixAllocMarker
CONFIG_KEY = "prefix-allocator-config"  # persisted index


class PrefixAllocator:
    def __init__(
        self,
        evb: OpenrEventBase,
        node_name: str,
        client: KvStoreClientInternal,
        seed_prefix: str,
        alloc_prefix_len: int,
        *,
        area: str = "0",
        prefix_updates_queue: Optional[ReplicateQueue[PrefixUpdateRequest]] = None,
        config_store: Optional[PersistentStore] = None,
    ) -> None:
        self.evb = evb
        self.node_name = node_name
        self.client = client
        self.seed = ipaddress.ip_network(seed_prefix)
        self.alloc_len = alloc_prefix_len
        assert alloc_prefix_len > self.seed.prefixlen, "alloc len must be longer"
        n_prefixes = 1 << (alloc_prefix_len - self.seed.prefixlen)
        self._prefix_updates_queue = prefix_updates_queue
        self.config_store = config_store
        self.my_prefix: Optional[str] = None
        self.range_allocator = RangeAllocator(
            evb,
            client,
            area,
            ALLOC_PREFIX_MARKER,
            node_name,
            self._on_allocated,
            (0, n_prefixes - 1),
        )

    def start(self) -> None:
        init = None
        if self.config_store is not None:
            raw = self.config_store.load(CONFIG_KEY)
            if raw is not None:
                try:
                    init = int(raw.decode())
                except ValueError:
                    init = None
        self.range_allocator.start_allocation(init)

    def _index_to_prefix(self, index: int) -> str:
        # i-th subprefix computed arithmetically (2^k subnets never
        # materialized)
        shift = self.seed.network_address.max_prefixlen - self.alloc_len
        base = int(self.seed.network_address) + (index << shift)
        return str(ipaddress.ip_network((base, self.alloc_len)))

    def _on_allocated(self, index: Optional[int]) -> None:
        if index is None:
            # lost allocation: withdraw
            if self.my_prefix is not None and self._prefix_updates_queue is not None:
                self._prefix_updates_queue.push(
                    PrefixUpdateRequest(
                        prefixes_to_del=[self.my_prefix],
                        type=PrefixType.PREFIX_ALLOCATOR,
                    )
                )
            self.my_prefix = None
            return
        self.my_prefix = self._index_to_prefix(index)
        log.info(
            "prefix-allocator %s: allocated index %d -> %s",
            self.node_name,
            index,
            self.my_prefix,
        )
        if self.config_store is not None:
            self.config_store.store(CONFIG_KEY, str(index).encode())
        if self._prefix_updates_queue is not None:
            self._prefix_updates_queue.push(
                PrefixUpdateRequest(
                    prefixes_to_add=[
                        PrefixEntry(
                            prefix=self.my_prefix,
                            type=PrefixType.PREFIX_ALLOCATOR,
                        )
                    ],
                    type=PrefixType.PREFIX_ALLOCATOR,
                )
            )

    def get_my_prefix(self) -> Optional[str]:
        return self.my_prefix

    def stop(self) -> None:
        self.range_allocator.stop()
