"""Distributed allocators over KvStore consensus."""

from .range_allocator import RangeAllocator
from .prefix_allocator import PrefixAllocator

__all__ = ["PrefixAllocator", "RangeAllocator"]
