"""Counter-hygiene checkers.

fb303-style counters only matter if an operator can see them.  The dump
path is ``OpenrCtrlHandler._all_counters`` (ctrl/server.py), which merges
each module's ``get_counters()`` / ``counters`` surface plus the queue
registry.  Three rules keep every bump site on that path:

- ``counter-name``: every counter *literal* bumped anywhere must follow
  the ``module.name`` convention — lowercase ``[a-z0-9_]`` segments, at
  least two, dot-separated — so prefix-based aggregation and the registry
  check below are meaningful.
- ``counter-registry``: the first segment must match a module surface
  consulted by ``_all_counters`` (discovered by parsing that method's own
  AST, so wiring a new module in automatically extends the allowed set),
  or an extra prefix granted in ``[tool.openr-analysis]``.  A counter that
  fails this is bumped into a dict nothing ever dumps.
- ``counter-duplicate``: no metric may be bumped under two spellings.
  Spellings are compared after normalizing a leading ``num_`` on each
  segment (``queue.x.num_overflows`` vs ``queue.x.overflows`` collide).

- ``counter-unbumped`` (the inverse direction): a counter *pre-seeded* in
  a registry literal — ``self.counters = {"mod.key": 0, ...}`` or the
  ``{k: 0 for k in MODULE_KEYS}`` comprehension over a module-level tuple
  of literals (the ``ENGINE_COUNTER_KEYS`` pattern) — that is never bumped
  anywhere in the analyzed tree.  A seeded-but-dead counter reads as a
  permanent zero on the operator surface, which is worse than absent: it
  asserts "this event never happens" while nothing measures it.  Only
  convention-clean (``module.name``) seeds are checked; bare-keyed mock
  surfaces are out of scope.

Bump sites recognized: ``*. _bump("lit", ...)`` calls and subscript
writes into counters-like dicts (``...counters["lit"] = / +=``).  The
``stats()`` dict literals in ``runtime/queue.py`` are treated as synthetic
``queue.<name>.<key>`` counters, because ``queue_counters`` exports them
verbatim under that prefix.  An ``export_histogram(counters, "<family>",
hist)`` call (obs/histogram.py) is a bump site for each
``<family>.p50_us/.p99_us/.p999_us`` percentile key it emits — the
family argument must be a string LITERAL at the call site so the wire
keys stay statically checkable.
"""

from __future__ import annotations

import ast
import re
from collections import defaultdict
from dataclasses import dataclass
from pathlib import Path

from .core import AnalysisConfig, Reporter, SourceFile

_NAME_RE = re.compile(r"[a-z][a-z0-9_]*(\.[a-z0-9_]+)+")
_SEGMENT_RE = re.compile(r"[a-z][a-z0-9_]*")


@dataclass(frozen=True)
class BumpSite:
    literal: str
    sf: SourceFile
    node: ast.AST
    #: synthetic sites (queue stats keys) skip the full-name lexical check
    synthetic: bool = False


def check(
    files: list[SourceFile],
    reporter: Reporter,
    config: AnalysisConfig,
    root: Path,
) -> None:
    sites: list[BumpSite] = []
    for sf in files:
        sites.extend(_collect_bumps(sf))
        if sf.rel.endswith("runtime/queue.py") or sf.rel == "queue.py":
            sites.extend(_collect_queue_stats_keys(sf))

    prefixes = _exported_prefixes(files)
    prefixes |= set(config.counter_extra_prefixes)

    well_named: list[BumpSite] = []
    for site in sites:
        if site.synthetic:
            key = site.literal.split(".")[-1]
            if _SEGMENT_RE.fullmatch(key):
                well_named.append(site)
            else:
                reporter.emit(
                    site.sf,
                    "counter-name",
                    site.node,
                    f"queue stats key '{key}' is not a valid counter segment "
                    "(lowercase [a-z0-9_]); it is exported as "
                    f"queue.<name>.{key}",
                )
            continue
        if _NAME_RE.fullmatch(site.literal):
            well_named.append(site)
        else:
            reporter.emit(
                site.sf,
                "counter-name",
                site.node,
                f"counter '{site.literal}' violates the module.name "
                "convention (lowercase dot-separated segments, at least "
                "two: e.g. 'kvstore.sent_publications')",
            )

    # registry reachability — only meaningful if we found (or were given)
    # an export surface to check against
    if prefixes:
        for site in well_named:
            first = site.literal.split(".")[0]
            if first not in prefixes:
                reporter.emit(
                    site.sf,
                    "counter-registry",
                    site.node,
                    f"counter '{site.literal}' has prefix '{first}' which is "
                    "not reachable from OpenrCtrlHandler._all_counters "
                    f"(exported surfaces: {', '.join(sorted(prefixes))}); "
                    "wire the module into the ctrl handler or rename the "
                    "counter onto an exported surface",
                )

    # seeded-but-never-bumped registry keys (inverse hygiene).  Seeds are
    # matched against every bump literal in the analyzed file set, so the
    # check is tree-wide when run over the package.
    bumped_literals = {s.literal for s in sites if not s.synthetic}
    for sf in files:
        for literal, node in _collect_seeds(sf):
            if literal not in bumped_literals:
                reporter.emit(
                    sf,
                    "counter-unbumped",
                    node,
                    f"counter '{literal}' is pre-seeded in a registry but "
                    "never bumped anywhere; it reads as a permanent zero on "
                    "the operator surface — bump it or drop the seed",
                )

    # duplicate spellings
    by_norm: dict[str, dict[str, list[BumpSite]]] = defaultdict(
        lambda: defaultdict(list)
    )
    for site in well_named:
        norm = _normalize(site.literal)
        by_norm[norm][site.literal].append(site)
    for norm, spellings in sorted(by_norm.items()):
        if len(spellings) < 2:
            continue
        names = sorted(spellings)
        for lit, sts in sorted(spellings.items()):
            others = [n for n in names if n != lit]
            for site in sts:
                reporter.emit(
                    site.sf,
                    "counter-duplicate",
                    site.node,
                    f"counter '{lit}' is also bumped as "
                    f"{', '.join(repr(o) for o in others)}; pick one "
                    "canonical spelling",
                )


def _normalize(literal: str) -> str:
    return ".".join(
        seg[4:] if seg.startswith("num_") and len(seg) > 4 else seg
        for seg in literal.split(".")
    )


# ---------------------------------------------------------------------------
# Bump-site collection
# ---------------------------------------------------------------------------


def _collect_bumps(sf: SourceFile) -> list[BumpSite]:
    out: list[BumpSite] = []
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr == "_bump"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                out.append(BumpSite(node.args[0].value, sf, node.args[0]))
                continue
            name = (
                f.id
                if isinstance(f, ast.Name)
                else f.attr
                if isinstance(f, ast.Attribute)
                else None
            )
            if (
                name == "export_histogram"
                and len(node.args) >= 2
                and isinstance(node.args[1], ast.Constant)
                and isinstance(node.args[1].value, str)
            ):
                fam = node.args[1].value
                for suffix in ("p50_us", "p99_us", "p999_us"):
                    out.append(BumpSite(f"{fam}.{suffix}", sf, node.args[1]))
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for tgt in targets:
                if not isinstance(tgt, ast.Subscript):
                    continue
                if not _is_counters_dict(tgt.value):
                    continue
                sl = tgt.slice
                if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                    out.append(BumpSite(sl.value, sf, sl))
    return out


def _collect_seeds(sf: SourceFile) -> list[tuple[str, ast.AST]]:
    """Registry seeds: convention-clean string keys of dict literals (or of
    ``{k: 0 for k in KEYS}`` comprehensions over module-level literal
    tuples) assigned to counters-like targets."""
    # module-level NAME = ("lit", ...) tuples, for the comprehension form
    mod_tuples: dict[str, list[ast.Constant]] = {}
    for node in sf.tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, (ast.Tuple, ast.List))
            and node.value.elts
            and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in node.value.elts
            )
        ):
            mod_tuples[node.targets[0].id] = list(node.value.elts)

    out: list[tuple[str, ast.AST]] = []
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        if not any(_is_counters_dict(t) for t in targets):
            continue
        if isinstance(value, ast.Dict):
            for k in value.keys:
                if (
                    isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                    and _NAME_RE.fullmatch(k.value)
                ):
                    out.append((k.value, k))
        elif isinstance(value, ast.DictComp) and value.generators:
            it = value.generators[0].iter
            if isinstance(it, ast.Name) and it.id in mod_tuples:
                for e in mod_tuples[it.id]:
                    if _NAME_RE.fullmatch(e.value):
                        out.append((e.value, e))
    return out


def _is_counters_dict(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Attribute):
        return "counters" in expr.attr
    if isinstance(expr, ast.Name):
        return "counters" in expr.id
    return False


def _collect_queue_stats_keys(sf: SourceFile) -> list[BumpSite]:
    out: list[BumpSite] = []
    for cls in sf.tree.body:
        if not isinstance(cls, ast.ClassDef):
            continue
        for meth in cls.body:
            if (
                isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef))
                and meth.name == "stats"
            ):
                for node in ast.walk(meth):
                    if isinstance(node, ast.Dict):
                        for k in node.keys:
                            if isinstance(k, ast.Constant) and isinstance(
                                k.value, str
                            ):
                                out.append(
                                    BumpSite(
                                        f"queue.x.{k.value}",
                                        sf,
                                        k,
                                        synthetic=True,
                                    )
                                )
    return out


# ---------------------------------------------------------------------------
# Export-surface discovery
# ---------------------------------------------------------------------------


def _exported_prefixes(files: list[SourceFile]) -> set[str]:
    """Parse OpenrCtrlHandler._all_counters for the module surfaces it dumps.

    Every ``self.<attr>`` the method touches is an exported surface; a call
    to ``queue_counters`` exports the ``queue`` prefix.  Counters are then
    required to lead with one of those attrs, so the check self-updates
    when a new module is wired into the handler.
    """
    prefixes: set[str] = set()
    for sf in files:
        for cls in ast.walk(sf.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for meth in cls.body:
                if (
                    not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef))
                    or meth.name != "_all_counters"
                ):
                    continue
                for node in ast.walk(meth):
                    if (
                        isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "self"
                    ):
                        prefixes.add(node.attr)
                    if isinstance(node, ast.Call):
                        f = node.func
                        name = (
                            f.id
                            if isinstance(f, ast.Name)
                            else f.attr
                            if isinstance(f, ast.Attribute)
                            else None
                        )
                        if name == "queue_counters":
                            prefixes.add("queue")
    return prefixes
