"""Program-level invariant auditor: jaxpr contracts for every jit root.

The AST families can only see source text.  This family — the ONLY one
that imports jax — proves properties of the *compiled programs*:

- it discovers every ``jax.jit`` root the AST pass knows about (the
  ``jit_paths`` set: ``ops/`` + ``decision/fleet.py``) plus the jit roots
  in ``device/engine.py`` and every bucket cell of the
  ``DeviceResidencyEngine`` AOT ladder;
- it runs a fixed set of deterministic CPU drivers (ring/grid fleets,
  residency-engine queries, KSP prefetch, protection what-ifs, direct
  kernel exercisers) with every root monkeypatched by a recording
  wrapper, so each root's *real production argument shapes* are captured
  without hand-maintaining spec tables;
- it re-traces each captured (root, spec) to a jaxpr and checks:

  ``program-donation``  every ``donate_argnums`` arg is actually aliased
                        by XLA.  jax matches donated inputs to outputs by
                        exact aval equality and silently DROPS the
                        donation otherwise (a warning at lowering is the
                        only trace) — the bug class that cost the engine
                        ladder its donation for a transposed return.
  ``program-dtype``     no float64 and no weak-type float promotion
                        anywhere in the jaxpr; the relax pipeline is
                        integer min-plus end to end, so floats are
                        allowed only for roots named in
                        ``program_float_allowed`` (loss kernels).
  ``program-callback``  no host callback / debug primitives — one
                        ``io_callback`` turns a resident program into a
                        per-sweep host round-trip.
  ``program-constants`` no closed-over constant above
                        ``program_const_max_bytes`` — embedded arrays
                        re-upload on every compile instead of living in
                        device residency.
  ``program-budget``    total jaxpr primitive count per program vs the
                        checked-in budget file
                        (``openr_tpu/analysis/program_budgets.json``) so
                        graph blowups fail loudly; regenerate with
                        ``--write-budgets`` after reviewing a growth.
  ``program-coverage``  a jit root no driver reached — keeps the driver
                        set honest as kernels are added.

Drivers force ``JAX_PLATFORMS=cpu`` tracing (no accelerator needed);
driver or trace failures raise :class:`AnalysisError` so the CLI exits 2
("broken analyzer"), never silently shrinking coverage.
"""

from __future__ import annotations

import ast
import functools
import importlib
import json
import os
import sys
import warnings
from pathlib import Path
from typing import Any, Callable, Iterator

from .core import (
    AnalysisConfig,
    AnalysisError,
    Reporter,
    SourceFile,
)

BUDGET_FILE = "openr_tpu/analysis/program_budgets.json"

#: extra files (beyond jit_paths) whose module-level jit roots are audited;
#: the residency engine's helper programs donate buffers and must stay
#: aliased just like the ladder cells
EXTRA_ROOT_FILES = ("openr_tpu/device/engine.py",)

#: at most this many distinct captured arg-specs are audited per root
MAX_SPECS_PER_ROOT = 4

_CALLBACK_PRIMITIVES = {
    "io_callback",
    "pure_callback",
    "python_callback",
    "callback",
    "debug_callback",
    "debug_print",
    "infeed",
    "outfeed",
}

_DONATION_WARNING = "Some donated buffers were not usable"


# ---------------------------------------------------------------------------
# Root discovery (AST, shared with the jit family)
# ---------------------------------------------------------------------------


def _root_files(
    files: list[SourceFile], config: AnalysisConfig, root: Path
) -> list[SourceFile]:
    """jit_paths + EXTRA_ROOT_FILES as SourceFiles, parsed from the tree
    regardless of what `targets` the caller passed (program rules always
    audit the whole tree)."""
    by_rel = {sf.rel: sf for sf in files}
    out: dict[str, SourceFile] = {}
    wanted: list[Path] = []
    for p in [*config.jit_paths, *EXTRA_ROOT_FILES]:
        wanted.append(root / p)
    from .core import walk_python_files

    for path in walk_python_files(wanted):
        try:
            rel = path.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = path.as_posix()
        if rel in by_rel:
            out[rel] = by_rel[rel]
            continue
        sf = SourceFile.parse(path, root)
        if sf is not None:
            out[rel] = sf
    return list(out.values())


def _discover_roots(root_files: list[SourceFile]):
    """(module, name) -> FuncRecord for every jitted def in the root set."""
    from .jit import _Index

    index = _Index(root_files)
    return {
        rec.key: rec
        for rec in index.funcs.values()
        if rec.is_jitted and not rec.module.startswith("tests")
    }


# ---------------------------------------------------------------------------
# Spec capture: monkeypatch roots, run drivers, record ShapeDtypeStructs
# ---------------------------------------------------------------------------


class _Recorder:
    """Records (args, kwargs) specs for every patched root invocation.

    Array-like leaves (device arrays, tracers, numpy arrays) become
    ShapeDtypeStructs; everything else (static ints/bools/strings/None)
    is kept verbatim so the spec replays through ``root.trace``."""

    def __init__(self) -> None:
        self.specs: dict[tuple[str, str], list[tuple]] = {}
        self._seen: set[tuple[tuple[str, str], str]] = set()

    def _to_spec(self, leaf):
        import jax

        aval = getattr(leaf, "aval", None)
        if aval is not None and hasattr(aval, "shape"):
            return jax.ShapeDtypeStruct(aval.shape, aval.dtype)
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            return jax.ShapeDtypeStruct(shape, dtype)
        return leaf

    def record(self, key: tuple[str, str], args: tuple, kwargs: dict) -> None:
        import jax

        spec = jax.tree_util.tree_map(self._to_spec, (args, dict(kwargs)))
        dedup = (key, str(jax.tree_util.tree_flatten(spec)))
        if dedup in self._seen:
            return
        if len(self.specs.get(key, ())) >= MAX_SPECS_PER_ROOT:
            return
        self._seen.add(dedup)
        self.specs.setdefault(key, []).append(spec)

    def wrap(self, key: tuple[str, str], orig: Callable) -> Callable:
        @functools.wraps(orig)
        def wrapper(*args, **kwargs):
            try:
                self.record(key, args, kwargs)
            except Exception:
                pass  # never let spec capture change driver behavior
            return orig(*args, **kwargs)

        wrapper.__openr_audit_orig__ = orig
        return wrapper


def _patch_roots(roots, recorder: _Recorder):
    """Install recording wrappers over every alias of every root across
    the imported openr_tpu modules.  Function-level ``from .x import f``
    re-resolves per call, but MODULE-level imports bind an alias in the
    importer's namespace — so every module attribute that *is* the root
    object gets patched, not just the defining module's.

    Returns an undo list of (module, attr, original)."""
    undo: list[tuple[Any, str, Any]] = []
    originals: dict[tuple[str, str], Any] = {}
    for (mod_name, fn_name), rec in roots.items():
        try:
            module = importlib.import_module(mod_name)
        except Exception as e:  # pragma: no cover - import errors are fatal
            raise AnalysisError(
                f"program auditor could not import {mod_name}: {e}"
            ) from e
        orig = getattr(module, fn_name, None)
        if orig is None or not callable(orig):
            continue
        originals[(mod_name, fn_name)] = orig
    # patch every alias (same object) in every loaded openr_tpu module
    for key, orig in originals.items():
        wrapper = recorder.wrap(key, orig)
        for mod in list(sys.modules.values()):
            name = getattr(mod, "__name__", "")
            if not name.startswith("openr_tpu"):
                continue
            for attr, val in list(vars(mod).items()):
                if val is orig:
                    undo.append((mod, attr, orig))
                    setattr(mod, attr, wrapper)
    return undo, originals


# ---------------------------------------------------------------------------
# Deterministic drivers
# ---------------------------------------------------------------------------


def _ring_link_state(n: int = 64, metric_fn=None, drop: dict | None = None):
    """64-node circulant ring (d = +-1, +-2): the smallest topology the
    banded kernel accepts, so the fleet warm paths actually engage (the
    ELL fallback ignores warm seeds and would hide those roots)."""
    from ..decision.link_state import LinkState
    from ..types import Adjacency, AdjacencyDatabase

    metric_fn = metric_fn or (lambda i, j: 20)
    drop = drop or {}

    def name(i: int) -> str:
        return f"r{i % n:03d}"

    ls = LinkState()
    for i in range(n):
        me = name(i)
        adjs = [
            Adjacency(
                other_node_name=name(i + d),
                if_name=f"{me}/{name(i + d)}",
                other_if_name=f"{name(i + d)}/{me}",
                metric=metric_fn(i, (i + d) % n),
                next_hop_v6=f"fe80::{i}:{d % 7}",
                next_hop_v4=f"10.0.{i}.{d % 7}",
            )
            for d in (1, -1, 2, -2)
            if d != drop.get(i)
        ]
        ls.update_adjacency_database(
            AdjacencyDatabase(
                this_node_name=me, adjacencies=adjs, node_label=1000 + i
            )
        )
    return ls


def _update_ring_node(
    ls, i: int, n: int = 64, metric_fn=None, drop=None, overloaded=False
):
    from ..types import Adjacency, AdjacencyDatabase

    metric_fn = metric_fn or (lambda i, j: 20)

    def name(j: int) -> str:
        return f"r{j % n:03d}"

    me = name(i)
    adjs = [
        Adjacency(
            other_node_name=name(i + d),
            if_name=f"{me}/{name(i + d)}",
            other_if_name=f"{name(i + d)}/{me}",
            metric=metric_fn(i, (i + d) % n),
            next_hop_v6=f"fe80::{i}:{d % 7}",
            next_hop_v4=f"10.0.{i}.{d % 7}",
        )
        for d in (1, -1, 2, -2)
        if d != drop
    ]
    ls.update_adjacency_database(
        AdjacencyDatabase(
            this_node_name=me,
            adjacencies=adjs,
            is_overloaded=overloaded,
            node_label=1000 + i,
        )
    )


def _drive_engine(state: dict) -> None:
    """Residency-engine ladder: small + full program shapes over two S
    buckets, plus an incremental masked-write sync.  The engines are kept
    in `state` so the ladder audit can read their _program_specs."""
    import numpy as np  # noqa: F401  (kept: drivers stay numpy-only)

    from ..decision.csr import CsrTopology
    from ..device.engine import DeviceResidencyEngine

    ls = _ring_link_state()
    engines = []
    for small_threshold in (1 << 21, 0):
        csr = CsrTopology.from_link_state(ls)
        eng = DeviceResidencyEngine(small_threshold=small_threshold)
        eng.spf_results(csr, ["r000"])  # S bucket 1
        eng.spf_results(csr, ["r001", "r002", "r003"])  # S bucket 8
        engines.append(eng)
    # attribute flaps -> incremental sync: a metric write (i32 masked
    # write) and an overload flip (bool masked write)
    _update_ring_node(ls, 5, metric_fn=lambda i, j: 35)
    csr.refresh(ls)
    eng.spf_results(csr, ["r004"])
    _update_ring_node(ls, 7, overloaded=True)
    csr.refresh(ls)
    eng.spf_results(csr, ["r006"])
    state["engines"] = engines


def _drive_rewire(state: dict) -> None:
    """Edge-set rewire rung: retire a ring link and then re-add it so the
    CSR slot freelist recycles the retired slots and the engine's
    masked-ROW writers (`_masked_write_rows_i32` / `_masked_write_rows_bool`
    for the changed ELL rows, plus the element writers for edge columns)
    record production arg shapes.  The asserts keep the driver honest: a
    demotion to restage would leave the row-writer roots spec-less and
    fail program-coverage with a much less actionable finding."""
    from ..decision.csr import CsrTopology
    from ..device.engine import DeviceResidencyEngine

    ls = _ring_link_state()
    csr = CsrTopology.from_link_state(ls)
    engine = DeviceResidencyEngine()
    engine.spf_results(csr, ["r000"])
    # link DOWN: bidirectional adjacency broken -> edge slots retire
    _update_ring_node(ls, 20, drop=1)
    assert csr.refresh(ls), "ring link drop must ride the rewire path"
    engine.spf_results(csr, ["r001"])
    # link back UP: the freelist hands the retired slots back
    _update_ring_node(ls, 20)
    assert csr.refresh(ls), "ring link re-add must ride the rewire path"
    engine.spf_results(csr, ["r002"])
    c = engine.get_counters()
    assert c["device.engine.full_restages"] == 1, c
    assert c["device.engine.rewires"] == 2, c
    assert c["device.engine.rewire_rows"] > 0, c


def _drive_fleet_ring(state: dict) -> None:
    """Fleet product on the banded ring: cold, warm-improve and warm-down
    rebuilds (the three reduced_all_sources entry modes)."""
    from ..decision.fleet import FleetViewCache

    dests = ["r000", "r031", "r063"]
    cache = FleetViewCache()
    ls = _ring_link_state()
    v1 = cache.view(ls, dests)
    assert v1 is not None and v1.converged
    state["fleet_view"] = v1
    # improvement-only change -> warm "improve" gate
    _update_ring_node(ls, 5, metric_fn=lambda i, j: 15)
    v2 = cache.view(ls, dests)
    assert v2 is not None and v2.converged
    # link DOWN -> certified affected-set warm start
    _update_ring_node(ls, 10, drop=1)
    v3 = cache.view(ls, dests)
    assert v3 is not None and v3.converged


def _drive_delta(state: dict) -> None:
    """Incremental delta rung (ops.delta): frontier certification +
    frontier-sized relax on a metric worsening, then an adjacency drop so
    the changed out-rows re-encode (delta_rows_bitmap) runs too.  The
    asserts keep the driver honest: a silent fallback to the full path
    would leave the delta roots spec-less and fail the audit later with
    a much less actionable finding."""
    from ..decision.fleet import FleetViewCache
    from ..device.engine import DeviceResidencyEngine

    ls = _ring_link_state()
    # full-width destination set: the frontier bound is relative to P
    # (2 * cols <= P), so a handful of columns cannot host a delta
    dests = [f"r{i:03d}" for i in range(64)]
    engine = DeviceResidencyEngine()
    cache = FleetViewCache(delta=True)
    v1 = cache.view(ls, dests, engine=engine)
    assert v1 is not None and v1.converged
    # metric worsening of ONE edge -> delta_frontier + delta_relax
    # (worsening a node's whole adjacency set drops every support of its
    # row and the full-width frontier correctly falls back instead)
    _update_ring_node(ls, 5, metric_fn=lambda i, j: 90 if j == 6 else 20)
    v2 = cache.view(ls, dests, engine=engine)
    assert v2 is not None and v2.converged and v2.warm_mode == "delta"
    # adjacency drop -> out-slot re-rank -> delta_rows_bitmap
    _update_ring_node(ls, 40, drop=1)
    v3 = cache.view(ls, dests, engine=engine)
    assert v3 is not None and v3.converged and v3.warm_mode == "delta"


def _drive_blocked(state: dict) -> None:
    """Node-axis sharding rung (parallel.blocked): force the blocked
    APSP through the fleet dispatch so all three phase kernels, the
    destination-column extract and the bitmap root record specs.  The
    threshold is dropped instead of env-forcing OPENR_NODE_SHARD so the
    audit run does not leak environment into other drivers; the asserts
    keep the driver honest — a silent fallback to the fused product
    would leave the blocked roots spec-less and fail the audit later
    with a much less actionable finding.

    Both pipeline settings run (pinned `pipeline_mode`, same no-leak
    discipline as the threshold): the default lookahead closure must
    record the fused `blocked_round_pipelined` root — donation has to
    survive the double-buffered panel carry — and the pinned-off run
    keeps the bulk-synchronous `blocked_outer` root audit-visible."""
    from ..decision.fleet import FleetViewCache
    from ..device.engine import DeviceResidencyEngine

    ls = _ring_link_state()
    engine = DeviceResidencyEngine()
    engine.blocked.node_shard_threshold = 0  # every N engages the rung
    cache = FleetViewCache()
    view = cache.view(ls, ["r000", "r031", "r063"], engine=engine)
    assert view is not None and view.converged and view.node_sharded
    assert engine.blocked.counters["mesh.blocked.products"] == 1
    assert engine.blocked.counters["mesh.blocked.fallbacks"] == 0
    # auto-on pipelining at n=64/tile=16 -> 4 rounds, 3 prefetches; a
    # demotion here would silently audit the wrong loop
    assert (
        engine.blocked.counters["mesh.blocked.pipeline_prefetch_issues"] > 0
    )
    assert engine.blocked.counters["mesh.blocked.pipeline_fallbacks"] == 0

    engine2 = DeviceResidencyEngine()
    engine2.blocked.node_shard_threshold = 0
    engine2.blocked.pipeline_mode = "0"  # pinned off: bulk loop
    view2 = FleetViewCache().view(ls, ["r000", "r031", "r063"], engine=engine2)
    assert view2 is not None and view2.converged and view2.node_sharded
    assert engine2.blocked.counters["mesh.blocked.products"] == 1
    assert (
        engine2.blocked.counters["mesh.blocked.pipeline_prefetch_issues"] == 0
    )


def _drive_pallas(state: dict) -> None:
    """Pallas kernel rung (ops.pallas_kernels): run both hand-tiled
    kernels in interpreter mode so their jit roots record specs — the
    fused verify+bitmap epilogue through the fleet product, and the
    blocked rank-B outer update through a 1-device blocked closure.
    The mode is pinned on the engine instead of env-forcing
    OPENR_PALLAS (the _drive_blocked discipline: no environment leaks
    into other drivers); the counter asserts keep the driver honest —
    a silent demotion would leave the pallas roots spec-less and fail
    the audit later with a much less actionable finding."""
    import jax

    from ..decision.fleet import FleetViewCache
    from ..device.engine import DeviceResidencyEngine
    from ..parallel.blocked import make_blocked_mesh

    ls = _ring_link_state()
    engine = DeviceResidencyEngine()
    engine.pallas_mode = "interpret"
    view = FleetViewCache().view(
        ls, ["r000", "r031", "r063"], engine=engine
    )
    assert view is not None and view.converged
    c = engine.get_counters()
    assert c["device.engine.pallas_products"] == 1
    assert c["device.engine.pallas_fallbacks"] == 0

    engine2 = DeviceResidencyEngine()
    engine2.pallas_mode = "interpret"
    engine2.blocked.node_shard_threshold = 0
    engine2.blocked._mesh = make_blocked_mesh(jax.devices()[:1])
    view2 = FleetViewCache().view(
        ls, ["r000", "r031", "r063"], engine=engine2
    )
    assert view2 is not None and view2.converged and view2.node_sharded
    c2 = engine2.get_counters()
    assert c2["device.engine.pallas_outer_updates"] > 0
    assert c2["device.engine.pallas_fallbacks"] == 0


def _drive_fleet_grid_ell(state: dict) -> None:
    """Fleet product on a grid: no banded structure, so the ELL fallback
    and its fixed-sweep kernels run."""
    from ..decision.fleet import FleetViewCache
    from ..decision.link_state import LinkState
    from ..utils.topo import grid_topology

    ls = LinkState()
    for db in grid_topology(4):
        ls.update_adjacency_database(db)
    nodes = sorted(ls.node_names)
    cache = FleetViewCache()
    view = cache.view(ls, [nodes[0], nodes[-1]])
    assert view is not None and view.converged


def _drive_allsources_legacy(state: dict) -> None:
    """The non-default reduced_all_sources paths: adaptive two-dispatch
    (fused=False) and the fixed-sweep fused product."""
    import numpy as np

    from ..ops import allsources as asrc

    view = state["fleet_view"]
    csr = view.csr
    dest_ids = np.asarray(
        [view._node_id[d] for d in view.dest_names], dtype=np.int32
    )
    runner = view._runner
    for kw in ({"fused": False}, {"fused": True, "n_sweeps": 96}):
        dist, bitmap, ok = asrc.reduced_all_sources(
            dest_ids,
            runner,
            view._out,
            csr.edge_metric,
            csr.edge_up,
            csr.node_overloaded,
            **kw,
        )
        assert ok
    # standalone early-exit kernel (the fused product inlines its own
    # while-loop, so this root only runs via the runner's progressive mode)
    _dist, _dag, ok = runner.run_once(dest_ids, 8, progressive=True)
    assert bool(ok)


def _drive_ksp(state: dict) -> None:
    """2-shortest-paths: the device-backend prefetch (masked batched SPF)
    and the fused KSP2 runner.  The fused runner needs a spare padding
    edge (n_edges < E_cap), which the 64-ring's exactly-full edge table
    does not leave — a 65-ring pads up to the next capacity bucket."""
    import numpy as np

    from ..decision.fleet import FleetViewCache
    from ..decision.spf_solver import DeviceSpfBackend
    from ..ops.ksp import FusedKsp2Runner
    from ..ops.protection import build_reverse_edge_ids

    ls = _ring_link_state()
    backend = DeviceSpfBackend(min_device_nodes=1, min_device_sources=1)
    backend.prefetch_kth_paths(ls, "r000", ["r005", "r010"])

    ls65 = _ring_link_state(65)
    view = FleetViewCache().view(ls65, ["r000", "r031"])
    assert view is not None and view.converged
    csr = view.csr
    e = csr.n_edges
    rev = np.asarray(build_reverse_edge_ids(csr.edge_src[:e], csr.edge_dst[:e]))
    fk = FusedKsp2Runner(
        view._runner,
        csr.edge_dst,
        e,
        len(csr.node_names),
        rev,
        [csr.edge_metric],
    )
    res = fk.run(
        csr.node_id["r000"],
        np.asarray(
            [csr.node_id["r005"], csr.node_id["r010"]], dtype=np.int32
        ),
    )
    assert len(res) == 1


def _drive_protection(state: dict) -> None:
    """SRLG what-if + TI-LFA reports (protection kernels and the legacy
    batched_sssp/sp_dag_mask relax they reuse)."""
    from ..decision.link_state import LinkState
    from ..decision.protection_api import ti_lfa, what_if
    from ..utils.topo import ring_topology

    ls = LinkState()
    for db in ring_topology(4):
        ls.update_adjacency_database(db)
    rows = what_if(ls, [[("r0", "r1")]])
    assert rows and rows[0]["unknown_links"] == []
    report = ti_lfa(ls, "r0")
    assert report["node"] == "r0"


def _drive_forward_direct(state: dict) -> None:
    """Direct exercisers for forward kernels not on the default dispatch
    paths: the host-staged CSR fallback (packed + full) and the legacy
    one-call forwards."""
    import numpy as np

    from ..decision.csr import CsrTopology
    from ..ops import sssp as ops

    ls = _ring_link_state()
    csr = CsrTopology.from_link_state(ls)
    # host-staged degradation-ladder path (spf_forward_full_packed)
    csr.spf_from(["r000", "r007"])
    src = np.asarray([csr.node_id["r000"]], dtype=np.int32)
    n_words = max(1, -(-csr.max_out_slots // 32))
    # bulk (non-packed) host-staged shape.  These exercisers ARE the
    # audit harness: they dispatch kernels directly, on purpose, to put a
    # spec on roots no production path reaches.
    # openr: disable=jit-unbucketed-dispatch
    ops.spf_forward_full(
        src,
        csr.ell,
        csr.edge_src,
        csr.edge_dst,
        csr.edge_metric,
        csr.edge_up,
        csr.node_overloaded,
        csr.out_slot,
        n_words,
        n_sweeps=96,
    )
    # legacy one-call forwards (kept exported for conformance + mesh)
    # openr: disable=jit-unbucketed-dispatch
    ops.spf_forward(
        src,
        csr.edge_src,
        csr.edge_dst,
        csr.edge_metric,
        csr.edge_up,
        csr.node_overloaded,
    )
    # openr: disable=jit-unbucketed-dispatch
    ops.spf_forward_ell(
        src,
        csr.ell,
        csr.edge_src,
        csr.edge_dst,
        csr.edge_metric,
        csr.edge_up,
        csr.node_overloaded,
    )


def _drive_te(state: dict) -> None:
    """Differentiable-TE soft kernels (the tree's only float jit roots)
    plus one exact-gate round trip: soft distances must anneal toward
    the exact solver's, the descent step must move metrics, and the
    rounded candidate must score through the uint32 product."""
    import numpy as np

    from ..te import TeOptimizer, TeProblem
    from ..te import soft
    from ..te.exact import INF32

    # 8-node ring with one chord: small, asymmetric, cyclic
    n = 8
    links = np.array([[i, (i + 1) % n] for i in range(n)] + [[0, 4]])
    mets = np.vstack([np.tile([1, 1], (n, 1)), [[2, 2]]])
    from benchmarks.synthetic import Topology

    topo = Topology.from_links("te_audit", n, links, mets)
    dests = np.array([0, 3], dtype=np.int32)
    demand = np.zeros((topo.node_capacity, 2), dtype=np.float32)
    demand[1:n] = 1.0
    demand[3, 1] = 0.0
    problem = TeProblem.from_topology(topo, dests, demand, metric_hi=8)

    import jax.numpy as jnp

    args = (
        jnp.asarray(problem.edge_src),
        jnp.asarray(problem.edge_dst),
        jnp.asarray(problem.edge_metric, dtype=jnp.float32),
        jnp.asarray(problem.edge_up),
        jnp.asarray(problem.node_overloaded),
        jnp.asarray(problem.dest_ids),
    )
    # audit-harness direct dispatch, same rationale as _drive_forward_direct
    dist = np.asarray(
        # openr: disable=jit-unbucketed-dispatch
        soft.soft_sssp(*args, np.float32(0.05), n_sweeps=8)
    )
    opt = TeOptimizer()
    ev = opt._evaluator(problem)
    exact = ev.distances(problem.edge_metric)
    finite = exact[:n] < INF32
    assert np.abs(dist[:n][finite] - exact[:n][finite]).max() < 0.5

    # one descent step + exact gate through the optimizer front-end
    # (traces soft_objective_value, te_descent_step, and the te_exact
    # dispatch path)
    res = opt.optimize(
        problem, steps=2, round_trips=1, n_sweeps=8, flow_sweeps=8
    )
    assert res.metrics.dtype == np.int32
    assert opt.get_counters()["te.steps"] == 2
    # openr: disable=jit-unbucketed-dispatch
    _ = soft.soft_objective_value(
        jnp.asarray(problem.edge_metric, dtype=jnp.float32),
        args[0], args[1], args[3], args[4], args[5],
        jnp.asarray(problem.demand, dtype=jnp.float32),
        jnp.asarray(problem.capacity, dtype=jnp.float32),
        np.float32(0.1), np.float32(0.1), n_sweeps=8, flow_sweeps=8,
    )


def _drive_snapshot(state: dict) -> None:
    """Engine-snapshot restore rungs over the banded ring: take a
    checkpoint, drift the donor mirror (replay rung: the engine's
    incremental ladder runs under restore), then install the serialized
    artifact into a fresh engine over a content-identical fresh mirror
    (install rung + manifest prewarm — the AOT lowering path records
    its specs with no example arrays), and finally demote against a
    drifted foreign mirror (cold rung: the ordinary restage).  The
    asserts keep the driver honest about which rung each step took."""
    from ..decision.csr import CsrTopology
    from ..device.engine import DeviceResidencyEngine
    from ..snapshot import EngineSnapshot

    ls = _ring_link_state()
    csr = CsrTopology.from_link_state(ls)
    donor = DeviceResidencyEngine()
    donor.spf_results(csr, ["r000"])  # compile the manifest's ladder key
    snap = EngineSnapshot.take(donor, csr)
    blob = snap.to_bytes()
    # donor drift -> replay rung (masked-write incremental under restore)
    _update_ring_node(ls, 9, metric_fn=lambda i, j: 31)
    assert csr.refresh(ls), "attribute flap must stay in place"
    assert snap.restore(donor, csr) == "replay"
    donor.spf_results(csr, ["r001"])
    # fresh replica, content-identical mirror -> install rung + prewarm
    fresh_ls = _ring_link_state()
    _update_ring_node(fresh_ls, 9, metric_fn=lambda i, j: 31)
    fresh_csr = CsrTopology.from_link_state(fresh_ls)
    joiner = DeviceResidencyEngine()
    warm = EngineSnapshot.take(donor, csr)
    assert warm.restore(joiner, fresh_csr) == "install"
    joiner.spf_results(fresh_csr, ["r002"])
    # stale serialized artifact vs a drifted foreign mirror -> cold rung
    drifted_ls = _ring_link_state()
    _update_ring_node(drifted_ls, 3, metric_fn=lambda i, j: 29)
    drifted_csr = CsrTopology.from_link_state(drifted_ls)
    cold_eng = DeviceResidencyEngine()
    assert EngineSnapshot.from_bytes(blob).restore(cold_eng, drifted_csr) == (
        "cold"
    )
    cold_eng.spf_results(drifted_csr, ["r003"])


DRIVERS: tuple[tuple[str, Callable[[dict], None]], ...] = (
    ("engine", _drive_engine),
    ("rewire", _drive_rewire),
    ("fleet_ring", _drive_fleet_ring),
    ("delta", _drive_delta),
    ("blocked", _drive_blocked),
    ("pallas", _drive_pallas),
    ("fleet_grid_ell", _drive_fleet_grid_ell),
    ("allsources_legacy", _drive_allsources_legacy),
    ("ksp", _drive_ksp),
    ("protection", _drive_protection),
    ("forward_direct", _drive_forward_direct),
    ("te", _drive_te),
    ("snapshot", _drive_snapshot),
)


def _run_drivers(roots, recorder: _Recorder) -> dict:
    state: dict = {}
    undo, originals = _patch_roots(roots, recorder)
    try:
        for name, driver in DRIVERS:
            try:
                driver(state)
            except Exception as e:
                raise AnalysisError(
                    f"program auditor driver '{name}' failed: "
                    f"{type(e).__name__}: {e}"
                ) from e
    finally:
        for mod, attr, orig in undo:
            setattr(mod, attr, orig)
    state["originals"] = originals
    return state


# ---------------------------------------------------------------------------
# Jaxpr checks
# ---------------------------------------------------------------------------


def _all_jaxprs(jaxpr) -> Iterator:
    import jax.core as core

    yield jaxpr
    for sub in core.subjaxprs(jaxpr):
        yield from _all_jaxprs(sub)


def _count_eqns(jaxpr) -> int:
    return sum(len(j.eqns) for j in _all_jaxprs(jaxpr))


def _iter_avals(jaxpr) -> Iterator:
    for j in _all_jaxprs(jaxpr):
        seen = set()
        for v in [
            *j.constvars,
            *j.invars,
            *j.outvars,
            *(v for e in j.eqns for v in [*e.invars, *e.outvars]),
        ]:
            if id(v) in seen:
                continue
            seen.add(id(v))
            aval = getattr(v, "aval", None)
            if aval is not None and hasattr(aval, "dtype"):
                yield aval


class _ProgramAudit:
    """Shared per-program checks; emission goes to the Reporter against a
    stable source location (the root's def line, or _forward_body for
    ladder cells)."""

    def __init__(
        self, reporter: Reporter, config: AnalysisConfig, root: Path
    ) -> None:
        self.reporter = reporter
        self.config = config
        self.root = root
        self.op_counts: dict[str, int] = {}
        self.primitive_counts: dict[str, dict[str, int]] = {}

    # -- donation -----------------------------------------------------------

    def check_donation(
        self, sf, node, label: str, fn, specs, donate: tuple
    ) -> None:
        import jax

        if not donate:
            return
        try:
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                lowered = jax.jit(fn, donate_argnums=donate).lower(*specs)
                text = lowered.as_text()
        except Exception as e:
            raise AnalysisError(
                f"program auditor could not lower {label}: {e}"
            ) from e
        dropped = [
            str(w.message)
            for w in caught
            if _DONATION_WARNING in str(w.message)
        ]
        if dropped or "tf.aliasing_output" not in text:
            detail = dropped[0].splitlines()[0] if dropped else (
                "no input/output aliasing in the lowered module"
            )
        else:
            return
        self.reporter.emit(
            sf,
            "program-donation",
            node,
            f"{label}: donate_argnums={tuple(donate)} is declared but XLA "
            f"drops the donation ({detail}); make the donated input's aval "
            "match an output exactly (same shape AND dtype, no transpose) "
            "or remove the donation request",
        )

    # -- jaxpr body ---------------------------------------------------------

    def check_jaxpr(self, sf, node, label: str, fn_name: str, closed) -> None:
        import numpy as np

        jaxpr = closed.jaxpr
        # dtype discipline
        float_ok = fn_name in self.config.program_float_allowed
        flagged_dtypes: set[str] = set()
        for aval in _iter_avals(jaxpr):
            dt = np.dtype(aval.dtype)
            weak = bool(getattr(aval, "weak_type", False))
            bad = (
                dt == np.float64
                or (dt.kind == "f" and weak)
                or (dt.kind == "f" and not float_ok)
            )
            if bad and dt.name not in flagged_dtypes:
                flagged_dtypes.add(dt.name)
                kind = (
                    "float64"
                    if dt == np.float64
                    else f"weak-type {dt.name}"
                    if weak
                    else dt.name
                )
                self.reporter.emit(
                    sf,
                    "program-dtype",
                    node,
                    f"{label}: {kind} value inside the traced program; the "
                    "relax pipeline is integer min-plus end to end — chase "
                    "the promotion (a Python float constant or np.float64 "
                    "default) or whitelist the root in "
                    "program_float_allowed",
                )
        # host callbacks
        prim_counts: dict[str, int] = {}
        for j in _all_jaxprs(jaxpr):
            for eqn in j.eqns:
                pname = eqn.primitive.name
                prim_counts[pname] = prim_counts.get(pname, 0) + 1
                if pname in _CALLBACK_PRIMITIVES or "callback" in pname:
                    self.reporter.emit(
                        sf,
                        "program-callback",
                        node,
                        f"{label}: host callback primitive '{pname}' in "
                        "the compiled program — every invocation is a "
                        "device->host round-trip inside the graph",
                    )
        # large closed-over constants
        limit = self.config.program_const_max_bytes
        for const in closed.consts:
            nbytes = getattr(const, "nbytes", None)
            if nbytes is None:
                arr = np.asarray(const)
                nbytes = arr.nbytes
            if nbytes > limit:
                shape = getattr(const, "shape", ())
                dtype = getattr(const, "dtype", type(const).__name__)
                self.reporter.emit(
                    sf,
                    "program-constants",
                    node,
                    f"{label}: closed-over constant {dtype}{list(shape)} "
                    f"({nbytes} bytes > {limit}) is embedded in the "
                    "program and re-uploaded per compile; pass it as an "
                    "argument so it lives in device residency",
                )
        # op-count bookkeeping (max across specs of the same program name)
        n = _count_eqns(jaxpr)
        if n > self.op_counts.get(label, -1):
            self.op_counts[label] = n
            self.primitive_counts[label] = prim_counts


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------


def check(
    files: list[SourceFile],
    reporter: Reporter,
    config: AnalysisConfig,
    root: Path,
    write_budgets: bool = False,
) -> dict[str, int]:
    """Run the program auditor; returns the measured op counts (the CLI
    uses them for --write-budgets)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import jax  # noqa: F401
    except Exception as e:  # pragma: no cover - jax is baked into the image
        raise AnalysisError(
            f"program rules need jax to trace programs: {e}"
        ) from e
    # roots reached only *while tracing* other roots (batched_sssp inside
    # spf_forward, ...) never run when the outer executable is already
    # cached — so a warm process (pytest after device tests) would lose
    # their specs and report phantom coverage gaps.  Start cold, always.
    jax.clear_caches()

    root_files = _root_files(files, config, root)
    roots = _discover_roots(root_files)
    if not roots:
        raise AnalysisError(
            "program auditor found no jit roots under "
            f"jit_paths={config.jit_paths}"
        )

    recorder = _Recorder()
    state = _run_drivers(roots, recorder)
    originals = state["originals"]

    audit = _ProgramAudit(reporter, config, root)

    # -- jit roots ----------------------------------------------------------
    for key, rec in sorted(roots.items()):
        mod_name, fn_name = key
        specs = recorder.specs.get(key)
        if not specs:
            if key in originals:
                reporter.emit(
                    rec.sf,
                    "program-coverage",
                    rec.node,
                    f"jit root {mod_name}.{fn_name} was never traced by "
                    "the program auditor's drivers; add a driver (or an "
                    "exerciser to _drive_forward_direct) in "
                    "openr_tpu/analysis/programs.py",
                )
            continue
        orig = originals[key]
        label = f"{mod_name}.{fn_name}"
        for args, kwargs in specs:
            try:
                traced = orig.trace(*args, **kwargs)
            except Exception as e:
                raise AnalysisError(
                    f"program auditor could not trace {label} with a "
                    f"captured spec: {type(e).__name__}: {e}"
                ) from e
            audit.check_jaxpr(rec.sf, rec.node, label, fn_name, traced.jaxpr)

    # -- residency-engine ladder cells --------------------------------------
    engine_sf, engine_node = _engine_location(root_files)
    for eng in state.get("engines", ()):
        for cell_key, (fn, specs, donate) in eng._program_specs.items():
            _topo, s_bucket, _n_words, _sweeps, small, use_metric = cell_key
            label = (
                "device.engine._forward_body"
                f"[s{s_bucket},{'packed' if small else 'full'},"
                f"{'metric' if use_metric else 'unit'}]"
            )
            audit.check_donation(
                engine_sf, engine_node, label, fn, specs, donate
            )
            try:
                traced = jax.jit(fn).trace(*specs)
            except Exception as e:
                raise AnalysisError(
                    f"program auditor could not trace ladder cell "
                    f"{label}: {e}"
                ) from e
            audit.check_jaxpr(
                engine_sf, engine_node, label, "_forward_body", traced.jaxpr
            )
        if not eng._program_specs:
            raise AnalysisError(
                "engine driver compiled no ladder programs; the audit "
                "would be vacuous"
            )

    # -- op-count budgets ---------------------------------------------------
    budget_path = root / BUDGET_FILE
    if write_budgets:
        budget_path.write_text(
            json.dumps(dict(sorted(audit.op_counts.items())), indent=2)
            + "\n",
            encoding="utf-8",
        )
    else:
        budgets = _load_budgets(budget_path)
        for label in sorted(audit.op_counts):
            count = audit.op_counts[label]
            sf, node = _budget_location(
                label, roots, engine_sf, engine_node
            )
            if label not in budgets:
                reporter.emit(
                    sf,
                    "program-budget",
                    node,
                    f"{label}: no op-count budget entry ({count} "
                    "primitives measured); run `python -m "
                    "openr_tpu.analysis --programs --write-budgets` and "
                    "commit the updated budget file",
                )
            elif count > budgets[label]:
                reporter.emit(
                    sf,
                    "program-budget",
                    node,
                    f"{label}: jaxpr grew to {count} primitives (budget "
                    f"{budgets[label]}); if the growth is intentional, "
                    "regenerate with --write-budgets and justify it in "
                    "the PR",
                )
    return audit.op_counts


def _load_budgets(path: Path) -> dict[str, int]:
    if not path.is_file():
        return {}
    try:
        data = json.loads(path.read_text(encoding="utf-8"))
    except (OSError, ValueError) as e:
        raise AnalysisError(f"unreadable budget file {path}: {e}") from e
    if not isinstance(data, dict):
        raise AnalysisError(f"budget file {path} must be a JSON object")
    return {str(k): int(v) for k, v in data.items()}


def _engine_location(root_files: list[SourceFile]):
    for sf in root_files:
        if sf.rel.endswith("device/engine.py"):
            for node in ast.walk(sf.tree):
                if (
                    isinstance(node, ast.FunctionDef)
                    and node.name == "_forward_body"
                ):
                    return sf, node
            return sf, (1, 0)
    raise AnalysisError("device/engine.py not found for the ladder audit")


def _budget_location(label, roots, engine_sf, engine_node):
    if label.startswith("device.engine."):
        return engine_sf, engine_node
    mod, _, fn = label.rpartition(".")
    rec = roots.get((mod, fn))
    if rec is not None:
        return rec.sf, rec.node
    return engine_sf, engine_node
