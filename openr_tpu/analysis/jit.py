"""Jit-hygiene checkers.

Five rules over the ``jax.jit`` call graphs rooted in the configured
``jit_paths`` (the ops/ kernels and the fleet dispatch layer):

- ``jit-host-sync``: inside a *traced* context (a jitted function, or any
  function it calls with traced arguments), constructs that force a
  device->host transfer or fail outright under tracing: ``float()`` /
  ``int()`` / ``bool()`` on traced values, ``np.asarray`` / ``np.array``,
  ``.block_until_ready()`` / ``.item()`` / ``.tolist()``, and ``print``.
- ``jit-tracer-branch``: Python ``if`` / ``while`` / ``assert`` (and
  conditional expressions) whose test depends on a tracer-derived value.
  ``x is None`` / ``x is not None`` checks and static extractors
  (``.shape`` / ``.ndim`` / ``.dtype`` / ``len()``) are exempt — those are
  concrete at trace time.
- ``jit-static-hygiene``: ``static_argnames`` naming a missing parameter,
  ``static_argnums`` out of range, static parameters with non-hashable
  (list/dict/set) defaults, and call sites passing a non-hashable literal
  into a static slot — each of these either breaks tracing or defeats the
  jit cache and recompiles every dispatch.
- ``jit-dispatch-sync``: in *host* code within the same files, implicit
  syncs on device-resident values returned by jitted calls —
  ``bool(ok)`` / ``int(blocks)`` / ``np.asarray(dist)`` and branches on
  them.  These are the per-dispatch-tax hazards: each one blocks the
  Python thread on the device stream.  Deliberate fetch points should use
  a single ``jax.device_get`` and/or carry a suppression explaining why
  the sync is intended.
- ``jit-unbucketed-dispatch``: daemon modules (analyzed files outside
  ``jit_paths`` and ``engine_dispatch_paths``) calling a jitted function
  directly.  Dispatch belongs behind the device-residency engine
  (``openr_tpu/device``), which buckets shapes, keeps the graph resident
  and accounts bytes/latency; a direct call silently gets none of that.
  Deliberate low-level call sites carry rationale suppressions.

The analysis is a fixpoint over an interprocedural "tracedness"
propagation: jitted roots seed their non-static parameters as traced;
direct calls (by local name, ``from x import f`` alias, or module-alias
attribute) into other analyzed files propagate per-parameter flags.
Method calls are not resolved — the kernels in scope are free functions,
which keeps the checker sound-enough without a type system.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path

from .core import AnalysisConfig, Reporter, Severity, SourceFile

# Attributes that are concrete (host) values even on tracers.
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
# Builtins whose result is a host value (and which sync/fail on tracers).
_CONVERSIONS = {"float", "int", "bool", "complex"}
# Builtins that never return device values.
_HOST_BUILTINS = {"len", "isinstance", "range", "enumerate", "zip", "max", "min"}
# numpy entry points that pull device buffers to host.
_NUMPY_SYNCS = {"numpy.asarray", "numpy.array", "numpy.copy"}
# method calls that sync or fail under trace
_SYNC_METHODS = {"block_until_ready", "item", "tolist"}


def _in_jit_paths(rel: str, config: AnalysisConfig) -> bool:
    for p in config.jit_paths:
        p = p.rstrip("/")
        if rel == p or rel.startswith(p + "/"):
            return True
    return False


def _module_name(rel: str) -> str:
    parts = rel[:-3].split("/") if rel.endswith(".py") else rel.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


@dataclass
class FuncRecord:
    node: ast.FunctionDef | ast.AsyncFunctionDef
    sf: SourceFile
    module: str
    name: str
    params: list[str] = field(default_factory=list)
    is_jitted: bool = False
    static_names: set[str] = field(default_factory=set)
    jit_site: ast.AST | None = None  # decorator / wrapping call node

    @property
    def key(self) -> tuple[str, str]:
        return (self.module, self.name)


@dataclass
class FileIndex:
    sf: SourceFile
    module: str
    #: local name -> ("module", dotted) or ("obj", dotted_module, attr)
    imports: dict[str, tuple[str, str] | tuple[str, str, str]] = field(
        default_factory=dict
    )
    #: module-level function defs by name
    funcs: dict[str, FuncRecord] = field(default_factory=dict)


class _Index:
    """Cross-file name resolution over the analyzed file set."""

    def __init__(self, files: list[SourceFile]) -> None:
        self.by_module: dict[str, FileIndex] = {}
        self.funcs: dict[tuple[str, str], FuncRecord] = {}
        for sf in files:
            fi = FileIndex(sf=sf, module=_module_name(sf.rel))
            self._index_imports(fi)
            self._index_functions(fi)
            self.by_module[fi.module] = fi
            for rec in fi.funcs.values():
                self.funcs[rec.key] = rec
        for fi in self.by_module.values():
            self._index_jit_roots(fi)

    # -- imports ----------------------------------------------------------
    def _index_imports(self, fi: FileIndex) -> None:
        pkg_parts = fi.module.split(".")
        for node in ast.walk(fi.sf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    dotted = a.name if a.asname else a.name.split(".")[0]
                    fi.imports[local] = ("module", dotted)
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = pkg_parts[: len(pkg_parts) - node.level]
                    mod = ".".join(base + (node.module.split(".") if node.module else []))
                else:
                    mod = node.module or ""
                for a in node.names:
                    if a.name == "*":
                        continue
                    local = a.asname or a.name
                    if (mod + "." + a.name) in _KNOWN_MODULE_PREFIXES or self._looks_like_module(
                        mod, a.name
                    ):
                        fi.imports[local] = ("module", mod + "." + a.name)
                    else:
                        fi.imports[local] = ("obj", mod, a.name)

    def _looks_like_module(self, mod: str, name: str) -> bool:
        # `from ..ops import allsources as asrc` — the imported name is a
        # sibling module iff an analyzed file maps to that dotted path.
        return (mod + "." + name) in self.by_module or name in ("numpy",)

    # -- functions --------------------------------------------------------
    def _index_functions(self, fi: FileIndex) -> None:
        for node in fi.sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi.funcs[node.name] = FuncRecord(
                    node=node,
                    sf=fi.sf,
                    module=fi.module,
                    name=node.name,
                    params=_param_names(node),
                )

    def _index_jit_roots(self, fi: FileIndex) -> None:
        for rec in fi.funcs.values():
            for deco in rec.node.decorator_list:
                statics = self._jit_statics(fi, deco, rec)
                if statics is not None:
                    rec.is_jitted = True
                    rec.static_names |= statics
                    rec.jit_site = deco
        # `fast_f = jax.jit(f, static_argnames=...)` at module level
        for node in fi.sf.tree.body:
            if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
                continue
            call = node.value
            if self.resolve_dotted(fi, call.func) != "jax.jit":
                continue
            if call.args and isinstance(call.args[0], ast.Name):
                rec = fi.funcs.get(call.args[0].id)
                if rec is not None:
                    rec.is_jitted = True
                    rec.static_names |= _statics_from_call(call, rec)
                    rec.jit_site = call
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            fi.imports[tgt.id] = ("obj", fi.module, rec.name)

    def _jit_statics(
        self, fi: FileIndex, deco: ast.AST, rec: FuncRecord
    ) -> set[str] | None:
        """Return static param names if `deco` jit-wraps the function."""
        if self.resolve_dotted(fi, deco) == "jax.jit":
            return set()
        if isinstance(deco, ast.Call):
            fdot = self.resolve_dotted(fi, deco.func)
            if fdot == "jax.jit":
                return _statics_from_call(deco, rec)
            if fdot == "functools.partial" and deco.args:
                if self.resolve_dotted(fi, deco.args[0]) == "jax.jit":
                    return _statics_from_call(deco, rec)
        return None

    # -- resolution -------------------------------------------------------
    def resolve_dotted(self, fi: FileIndex, node: ast.AST) -> str | None:
        """Resolve an expression to a dotted path like 'jax.numpy.asarray'."""
        if isinstance(node, ast.Name):
            ent = fi.imports.get(node.id)
            if ent is None:
                if node.id in fi.funcs:
                    return fi.module + "." + node.id
                return None
            if ent[0] == "module":
                return ent[1]
            return ent[1] + "." + ent[2]
        if isinstance(node, ast.Attribute):
            base = self.resolve_dotted(fi, node.value)
            if base is None:
                return None
            return base + "." + node.attr
        return None

    def resolve_func(self, fi: FileIndex, node: ast.AST) -> FuncRecord | None:
        dotted = self.resolve_dotted(fi, node)
        if dotted is None:
            return None
        mod, _, name = dotted.rpartition(".")
        return self.funcs.get((mod, name))


_KNOWN_MODULE_PREFIXES = {"jax.numpy", "jax.lax", "jax.random", "numpy.linalg"}


def _param_names(node: ast.FunctionDef | ast.AsyncFunctionDef | ast.Lambda) -> list[str]:
    a = node.args
    return [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]


def _statics_from_call(call: ast.Call, rec: FuncRecord) -> set[str]:
    out: set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            out |= set(_const_strs(kw.value))
        elif kw.arg == "static_argnums":
            for idx in _const_ints(kw.value):
                if 0 <= idx < len(rec.params):
                    out.add(rec.params[idx])
    return out


def _const_strs(node: ast.AST) -> list[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.append(el.value)
        return out
    return []


def _const_ints(node: ast.AST) -> list[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [
            el.value
            for el in node.elts
            if isinstance(el, ast.Constant) and isinstance(el.value, int)
        ]
    return []


# ---------------------------------------------------------------------------
# Traced-context analysis
# ---------------------------------------------------------------------------


class _TracedWalker:
    """Walk one function body with a set of traced names, emitting findings
    and enqueuing callees that receive traced arguments."""

    def __init__(
        self,
        index: _Index,
        fi: FileIndex,
        reporter: Reporter,
        enqueue,
    ) -> None:
        self.index = index
        self.fi = fi
        self.reporter = reporter
        self.enqueue = enqueue
        self.sf = fi.sf

    # -- tracedness -------------------------------------------------------
    def traced(self, node: ast.AST, env: set[str]) -> bool:
        if isinstance(node, ast.Name):
            return node.id in env
        if isinstance(node, (ast.Constant, ast.JoinedStr)):
            return False
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self.traced(node.value, env)
        if isinstance(node, ast.Call):
            return self._call_traced(node, env)
        if isinstance(node, ast.Subscript):
            return self.traced(node.value, env) or self.traced(node.slice, env)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.traced(el, env) for el in node.elts)
        if isinstance(node, ast.Starred):
            return self.traced(node.value, env)
        if isinstance(node, ast.BinOp):
            return self.traced(node.left, env) or self.traced(node.right, env)
        if isinstance(node, ast.UnaryOp):
            return self.traced(node.operand, env)
        if isinstance(node, ast.BoolOp):
            return any(self.traced(v, env) for v in node.values)
        if isinstance(node, ast.Compare):
            return self.traced(node.left, env) or any(
                self.traced(c, env) for c in node.comparators
            )
        if isinstance(node, ast.IfExp):
            return any(
                self.traced(x, env) for x in (node.test, node.body, node.orelse)
            )
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            inner = set(env)
            for gen in node.generators:
                if self.traced(gen.iter, env):
                    inner |= _target_names(gen.target)
            return self.traced(node.elt, inner)
        if isinstance(node, ast.Slice):
            return any(
                self.traced(x, env)
                for x in (node.lower, node.upper, node.step)
                if x is not None
            )
        return False

    def _call_traced(self, node: ast.Call, env: set[str]) -> bool:
        if isinstance(node.func, ast.Name):
            if node.func.id in _CONVERSIONS or node.func.id in _HOST_BUILTINS:
                return False
        dotted = self.index.resolve_dotted(self.fi, node.func)
        if dotted is not None and (
            dotted == "jax.device_get" or dotted.endswith(".device_get")
        ):
            return False
        args_traced = any(self.traced(a, env) for a in node.args) or any(
            self.traced(kw.value, env) for kw in node.keywords
        )
        # A call on a traced callable (e.g. a partial over traced operands)
        # yields a traced value even with no traced args.
        return args_traced or self.traced(node.func, env)

    def branch_traced(self, node: ast.AST, env: set[str]) -> bool:
        """Tracedness of a branch test, with trace-time-concrete exemptions."""
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None` are concrete under trace.
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops) and all(
                isinstance(c, ast.Constant) and c.value is None
                for c in node.comparators
            ):
                return False
        if isinstance(node, ast.BoolOp):
            return any(self.branch_traced(v, env) for v in node.values)
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
            return self.branch_traced(node.operand, env)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            # bool(x)/int(x) in a test is reported as jit-host-sync already.
            if node.func.id in _CONVERSIONS:
                return False
        return self.traced(node, env)

    # -- body walk --------------------------------------------------------
    def walk_body(self, body: list[ast.stmt], env: set[str]) -> None:
        for stmt in body:
            self._stmt(stmt, env)

    def _stmt(self, stmt: ast.stmt, env: set[str]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            inner = set(env) | set(_param_names(stmt))
            inner.add(stmt.name)
            env.add(stmt.name)  # calls to it yield traced values
            self.walk_body(stmt.body, inner)
            return
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._expr(stmt.value, env)
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = stmt.value
            if value is not None:
                self._expr(value, env)
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            )
            if isinstance(stmt, ast.AugAssign):
                flag = value is not None and (
                    self.traced(value, env) or self.traced(stmt.target, env)
                )
            else:
                flag = value is not None and self.traced(value, env)
            for tgt in targets:
                self._bind(tgt, value, flag, env)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._expr(stmt.test, env)
            if self.branch_traced(stmt.test, env):
                kind = "if" if isinstance(stmt, ast.If) else "while"
                self.reporter.emit(
                    self.sf,
                    "jit-tracer-branch",
                    stmt,
                    f"`{kind}` on a tracer-derived value "
                    f"({ast.unparse(stmt.test)[:60]}); use lax.cond/select or "
                    "hoist the decision out of the jitted function",
                )
            self.walk_body(stmt.body, env)
            self.walk_body(stmt.orelse, env)
            return
        if isinstance(stmt, ast.Assert):
            self._expr(stmt.test, env)
            if self.branch_traced(stmt.test, env):
                self.reporter.emit(
                    self.sf,
                    "jit-tracer-branch",
                    stmt,
                    "`assert` on a tracer-derived value; use "
                    "checkify or move the check to the host",
                )
            return
        if isinstance(stmt, ast.For):
            self._expr(stmt.iter, env)
            if self.traced(stmt.iter, env):
                for name in _target_names(stmt.target):
                    env.add(name)
            self.walk_body(stmt.body, env)
            self.walk_body(stmt.orelse, env)
            return
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                self._expr(item.context_expr, env)
            self.walk_body(stmt.body, env)
            return
        if isinstance(stmt, ast.Try):
            self.walk_body(stmt.body, env)
            for h in stmt.handlers:
                self.walk_body(h.body, env)
            self.walk_body(stmt.orelse, env)
            self.walk_body(stmt.finalbody, env)
            return
        if isinstance(stmt, ast.Expr):
            self._expr(stmt.value, env)
            return
        # pass/break/continue/raise/global/etc.: nothing traced to track
        if isinstance(stmt, ast.Raise) and stmt.exc is not None:
            self._expr(stmt.exc, env)

    def _bind(
        self, tgt: ast.AST, value: ast.AST | None, flag: bool, env: set[str]
    ) -> None:
        if isinstance(tgt, ast.Name):
            if flag:
                env.add(tgt.id)
            else:
                env.discard(tgt.id)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            if isinstance(value, (ast.Tuple, ast.List)) and len(value.elts) == len(
                tgt.elts
            ):
                for t, v in zip(tgt.elts, value.elts):
                    self._bind(t, v, self.traced(v, env), env)
            else:
                for t in tgt.elts:
                    self._bind(t, None, flag, env)
        # attribute/subscript targets: no name to track

    # -- expression checks ------------------------------------------------
    def _expr(self, node: ast.AST, env: set[str]) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Lambda):
                inner = set(env) | set(_param_names(sub))
                self._expr(sub.body, inner)
            elif isinstance(sub, ast.IfExp):
                if self.branch_traced(sub.test, env):
                    self.reporter.emit(
                        self.sf,
                        "jit-tracer-branch",
                        sub,
                        "conditional expression on a tracer-derived value; "
                        "use jnp.where / lax.select",
                    )
            elif isinstance(sub, ast.Call):
                self._check_call(sub, env)

    def _check_call(self, node: ast.Call, env: set[str]) -> None:
        args_traced = any(self.traced(a, env) for a in node.args) or any(
            self.traced(kw.value, env) for kw in node.keywords
        )
        if isinstance(node.func, ast.Name):
            fname = node.func.id
            if fname in _CONVERSIONS and args_traced:
                self.reporter.emit(
                    self.sf,
                    "jit-host-sync",
                    node,
                    f"{fname}() on a traced value fails under jit "
                    "(concretization of a tracer); compute on-device or "
                    "return the value and convert on the host",
                )
                return
            if fname == "print":
                self.reporter.emit(
                    self.sf,
                    "jit-host-sync",
                    node,
                    "print inside traced code runs at trace time only; "
                    "use jax.debug.print",
                )
                return
        dotted = self.index.resolve_dotted(self.fi, node.func)
        if dotted in _NUMPY_SYNCS and args_traced:
            self.reporter.emit(
                self.sf,
                "jit-host-sync",
                node,
                f"{dotted.replace('numpy', 'np')} on a traced value forces a "
                "host transfer and fails under jit; use jnp instead",
            )
            return
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _SYNC_METHODS
            and self.traced(node.func.value, env)
        ):
            self.reporter.emit(
                self.sf,
                "jit-host-sync",
                node,
                f".{node.func.attr}() on a traced value syncs/fails under jit",
            )
            return
        # propagate into analyzed callees receiving traced arguments
        rec = self.index.resolve_func(self.fi, node.func)
        if rec is not None and not rec.is_jitted and args_traced:
            traced_params: set[str] = set()
            for i, a in enumerate(node.args):
                if i < len(rec.params) and self.traced(a, env):
                    traced_params.add(rec.params[i])
            for kw in node.keywords:
                if kw.arg in rec.params and self.traced(kw.value, env):
                    traced_params.add(kw.arg)
            if traced_params:
                self.enqueue(rec, frozenset(traced_params))


# ---------------------------------------------------------------------------
# Static-arg hygiene
# ---------------------------------------------------------------------------

_NON_HASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.SetComp, ast.DictComp)


def _check_static_hygiene(index: _Index, reporter: Reporter, fi: FileIndex) -> None:
    for rec in fi.funcs.values():
        if not rec.is_jitted or rec.jit_site is None:
            continue
        site = rec.jit_site
        declared: list[str] = []
        nums: list[int] = []
        if isinstance(site, ast.Call):
            for kw in site.keywords:
                if kw.arg == "static_argnames":
                    declared = _const_strs(kw.value)
                elif kw.arg == "static_argnums":
                    nums = _const_ints(kw.value)
        for name in declared:
            if name not in rec.params:
                reporter.emit(
                    fi.sf,
                    "jit-static-hygiene",
                    site,
                    f"static_argnames names '{name}' which is not a parameter "
                    f"of {rec.name}()",
                )
        for idx in nums:
            if not (0 <= idx < len(rec.params)):
                reporter.emit(
                    fi.sf,
                    "jit-static-hygiene",
                    site,
                    f"static_argnums index {idx} is out of range for "
                    f"{rec.name}() with {len(rec.params)} parameters",
                )
        # non-hashable defaults on static params recompile on every call
        a = rec.node.args
        pos = a.posonlyargs + a.args
        for p, d in zip(pos[len(pos) - len(a.defaults) :], a.defaults):
            if p.arg in rec.static_names and isinstance(d, _NON_HASHABLE):
                reporter.emit(
                    fi.sf,
                    "jit-static-hygiene",
                    d,
                    f"static parameter '{p.arg}' of {rec.name}() has a "
                    "non-hashable default; jit static args must be hashable",
                )
        for p, d in zip(a.kwonlyargs, a.kw_defaults):
            if d is not None and p.arg in rec.static_names and isinstance(
                d, _NON_HASHABLE
            ):
                reporter.emit(
                    fi.sf,
                    "jit-static-hygiene",
                    d,
                    f"static parameter '{p.arg}' of {rec.name}() has a "
                    "non-hashable default; jit static args must be hashable",
                )
    # call sites passing non-hashable literals into static slots
    for node in ast.walk(fi.sf.tree):
        if not isinstance(node, ast.Call):
            continue
        rec = index.resolve_func(fi, node.func)
        if rec is None or not rec.is_jitted or not rec.static_names:
            continue
        for i, arg in enumerate(node.args):
            if i < len(rec.params) and rec.params[i] in rec.static_names:
                if isinstance(arg, _NON_HASHABLE):
                    reporter.emit(
                        fi.sf,
                        "jit-static-hygiene",
                        arg,
                        f"non-hashable literal passed to static parameter "
                        f"'{rec.params[i]}' of {rec.name}(); every call "
                        "re-traces — pass a tuple or hoist to a constant",
                    )
        for kw in node.keywords:
            if kw.arg in rec.static_names and isinstance(kw.value, _NON_HASHABLE):
                reporter.emit(
                    fi.sf,
                    "jit-static-hygiene",
                    kw.value,
                    f"non-hashable literal passed to static parameter "
                    f"'{kw.arg}' of {rec.name}(); every call re-traces — "
                    "pass a tuple or hoist to a constant",
                )


# ---------------------------------------------------------------------------
# Host-dispatch sync analysis (jit-dispatch-sync)
# ---------------------------------------------------------------------------


class _DispatchWalker:
    """Track device-derived (DD) values through host dispatch code."""

    def __init__(self, index: _Index, fi: FileIndex, reporter: Reporter) -> None:
        self.index = index
        self.fi = fi
        self.reporter = reporter
        self.sf = fi.sf
        #: (module, func) -> returns-device-derived
        self.ret_dd = _RET_DD_CACHE

    # -- DD classification -------------------------------------------------
    def dd(self, node: ast.AST, env: set[str]) -> bool:
        if isinstance(node, ast.Name):
            return node.id in env
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            if (
                isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and ("self." + node.attr) in env
            ):
                return True
            return self.dd(node.value, env)
        if isinstance(node, ast.Subscript):
            return self.dd(node.value, env)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.dd(el, env) for el in node.elts)
        if isinstance(node, ast.Call):
            return self.call_dd(node, env)
        if isinstance(node, ast.BinOp):
            return self.dd(node.left, env) or self.dd(node.right, env)
        if isinstance(node, ast.UnaryOp):
            return self.dd(node.operand, env)
        if isinstance(node, ast.Compare):
            return self.dd(node.left, env) or any(
                self.dd(c, env) for c in node.comparators
            )
        if isinstance(node, ast.BoolOp):
            return any(self.dd(v, env) for v in node.values)
        if isinstance(node, ast.IfExp):
            return self.dd(node.body, env) or self.dd(node.orelse, env)
        return False

    def call_dd(self, node: ast.Call, env: set[str]) -> bool:
        if isinstance(node.func, ast.Name):
            if node.func.id in _CONVERSIONS or node.func.id in _HOST_BUILTINS:
                return False
            if node.func.id in env and node.func.id.startswith("__local_fn_"):
                return True
        dotted = self.index.resolve_dotted(self.fi, node.func)
        if dotted is not None:
            if dotted in ("jax.device_get", "jax.block_until_ready"):
                return False
            if dotted in _NUMPY_SYNCS or dotted.startswith("numpy."):
                return False
            if dotted.startswith("jax.numpy.") or dotted.startswith("jax.lax."):
                return True
            mod, _, fname = dotted.rpartition(".")
            rec = self.index.funcs.get((mod, fname))
            if rec is not None:
                if rec.is_jitted:
                    return True
                if self.ret_dd.get(rec.key, False):
                    return True
        # local nested function known to return device values
        if isinstance(node.func, ast.Name) and ("fn:" + node.func.id) in env:
            return True
        return False

    # -- walk --------------------------------------------------------------
    def walk_function(
        self, node: ast.FunctionDef | ast.AsyncFunctionDef, env: set[str]
    ) -> None:
        self.walk_body(node.body, env)

    def walk_body(self, body: list[ast.stmt], env: set[str]) -> None:
        for stmt in body:
            self._stmt(stmt, env)

    def _stmt(self, stmt: ast.stmt, env: set[str]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested dispatch helper: walk its body with the outer env and
            # record whether it returns device-derived values
            inner = set(env)
            self.walk_body(stmt.body, inner)
            if self._returns_dd(stmt, env):
                env.add("fn:" + stmt.name)
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            if stmt.value is not None:
                self._expr(stmt.value, env)
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            )
            flag = stmt.value is not None and self.dd(stmt.value, env)
            for tgt in targets:
                self._bind(tgt, stmt.value, flag, env)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._expr(stmt.test, env)
            if self._branch_dd(stmt.test, env):
                kind = "if" if isinstance(stmt, ast.If) else "while"
                self.reporter.emit(
                    self.sf,
                    "jit-dispatch-sync",
                    stmt,
                    f"`{kind}` on a device value blocks on the device stream "
                    f"({ast.unparse(stmt.test)[:60]}); fetch once with "
                    "jax.device_get and branch on the host value",
                    severity=Severity.WARNING,
                )
            self.walk_body(stmt.body, env)
            self.walk_body(stmt.orelse, env)
            return
        if isinstance(stmt, ast.Assert):
            self._expr(stmt.test, env)
            if self._branch_dd(stmt.test, env):
                self.reporter.emit(
                    self.sf,
                    "jit-dispatch-sync",
                    stmt,
                    "`assert` on a device value forces a sync; fetch once "
                    "with jax.device_get",
                    severity=Severity.WARNING,
                )
            return
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._expr(stmt.value, env)
            return
        if isinstance(stmt, ast.For):
            self._expr(stmt.iter, env)
            if self.dd(stmt.iter, env):
                for name in _target_names(stmt.target):
                    env.add(name)
            self.walk_body(stmt.body, env)
            self.walk_body(stmt.orelse, env)
            return
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                self._expr(item.context_expr, env)
            self.walk_body(stmt.body, env)
            return
        if isinstance(stmt, ast.Try):
            self.walk_body(stmt.body, env)
            for h in stmt.handlers:
                self.walk_body(h.body, env)
            self.walk_body(stmt.orelse, env)
            self.walk_body(stmt.finalbody, env)
            return
        if isinstance(stmt, ast.Expr):
            self._expr(stmt.value, env)
            return
        if isinstance(stmt, ast.Raise) and stmt.exc is not None:
            self._expr(stmt.exc, env)

    def _bind(
        self, tgt: ast.AST, value: ast.AST | None, flag: bool, env: set[str]
    ) -> None:
        if isinstance(tgt, ast.Name):
            if flag:
                env.add(tgt.id)
            else:
                env.discard(tgt.id)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            if isinstance(value, (ast.Tuple, ast.List)) and len(value.elts) == len(
                tgt.elts
            ):
                for t, v in zip(tgt.elts, value.elts):
                    self._bind(t, v, self.dd(v, env), env)
            else:
                for t in tgt.elts:
                    self._bind(t, None, flag, env)
        elif (
            isinstance(tgt, ast.Attribute)
            and isinstance(tgt.value, ast.Name)
            and tgt.value.id == "self"
        ):
            if flag:
                env.add("self." + tgt.attr)

    def _returns_dd(
        self, node: ast.FunctionDef | ast.AsyncFunctionDef, env: set[str]
    ) -> bool:
        inner = set(env)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Return) and sub.value is not None:
                if self.dd(sub.value, inner):
                    return True
        return False

    def _branch_dd(self, node: ast.AST, env: set[str]) -> bool:
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops) and all(
                isinstance(c, ast.Constant) and c.value is None
                for c in node.comparators
            ):
                return False
        if isinstance(node, ast.BoolOp):
            return any(self._branch_dd(v, env) for v in node.values)
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
            return self._branch_dd(node.operand, env)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            if node.func.id in _CONVERSIONS:
                return False  # the conversion itself is flagged
        return self.dd(node, env)

    def _expr(self, node: ast.AST, env: set[str]) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Lambda):
                self._lambda(sub, env)
            elif isinstance(sub, ast.Call):
                self._check_call(sub, env)

    def _lambda(self, node: ast.Lambda, env: set[str]) -> None:
        inner = set(env) - set(_param_names(node))
        for sub in ast.walk(node.body):
            if isinstance(sub, ast.Call):
                self._check_call(sub, inner)

    def _check_call(self, node: ast.Call, env: set[str]) -> None:
        args_dd = any(self.dd(a, env) for a in node.args)
        if isinstance(node.func, ast.Name) and node.func.id in _CONVERSIONS and args_dd:
            self.reporter.emit(
                self.sf,
                "jit-dispatch-sync",
                node,
                f"{node.func.id}() on a device value is an implicit sync; "
                "batch fetches through a single jax.device_get",
                severity=Severity.WARNING,
            )
            return
        dotted = self.index.resolve_dotted(self.fi, node.func)
        if dotted in _NUMPY_SYNCS and args_dd:
            self.reporter.emit(
                self.sf,
                "jit-dispatch-sync",
                node,
                f"{dotted.replace('numpy', 'np')} on a device value is an "
                "implicit sync; batch fetches through a single jax.device_get",
                severity=Severity.WARNING,
            )


_RET_DD_CACHE: dict[tuple[str, str], bool] = {}


def _compute_ret_dd(index: _Index, scope: list[FileIndex]) -> None:
    """Fixpoint: which module-level functions return device-derived values."""
    _RET_DD_CACHE.clear()
    changed = True
    while changed:
        changed = False
        for fi in scope:
            walker = _DispatchWalker(index, fi, _NullReporter())
            for rec in fi.funcs.values():
                if rec.is_jitted or _RET_DD_CACHE.get(rec.key, False):
                    continue
                # simulate the body to build a local DD env, then test returns
                env: set[str] = set()
                try:
                    walker.walk_body_silent(rec.node.body, env)
                except RecursionError:  # pragma: no cover - defensive
                    continue
                for sub in ast.walk(rec.node):
                    if isinstance(sub, ast.Return) and sub.value is not None:
                        if walker.dd(sub.value, env):
                            _RET_DD_CACHE[rec.key] = True
                            changed = True
                            break


class _NullReporter:
    def emit(self, *a, **kw) -> None:
        pass


def _walk_body_silent(self, body, env):
    """Body walk that only updates the env (no findings emitted)."""
    saved = self.reporter
    self.reporter = _NullReporter()
    try:
        self.walk_body(body, env)
    finally:
        self.reporter = saved


_DispatchWalker.walk_body_silent = _walk_body_silent


# ---------------------------------------------------------------------------
# Engine-bypass detection (jit-unbucketed-dispatch)
# ---------------------------------------------------------------------------


def _in_engine_paths(rel: str, config: AnalysisConfig) -> bool:
    for p in config.engine_dispatch_paths:
        p = p.rstrip("/")
        if rel == p or rel.startswith(p + "/"):
            return True
    return False


def _check_unbucketed_dispatch(
    files: list[SourceFile], reporter: Reporter, config: AnalysisConfig
) -> None:
    """Daemon modules must not dispatch jitted kernels directly.

    Every analyzed file outside ``jit_paths`` (the kernel/dispatch layer)
    and ``engine_dispatch_paths`` (the device-residency engine) is daemon
    code: a direct call to a jitted function there bypasses the engine
    front-end, so the dispatch misses shape bucketing, residency sync and
    the device.engine.* accounting.  Deliberate low-level call sites (the
    host-mirror library, protection API) carry rationale suppressions.
    """
    index = _Index(files)
    for sf in files:
        if _in_jit_paths(sf.rel, config) or _in_engine_paths(sf.rel, config):
            continue
        fi = index.by_module.get(_module_name(sf.rel))
        if fi is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            rec = index.resolve_func(fi, node.func)
            if rec is None or not rec.is_jitted:
                continue
            reporter.emit(
                sf,
                "jit-unbucketed-dispatch",
                node,
                f"direct dispatch of jitted {rec.name}() from a daemon "
                "module; route through the device engine front-end "
                "(DeviceResidencyEngine.spf_results/dispatch) so shape "
                "bucketing, residency and accounting apply",
            )


def _target_names(tgt: ast.AST) -> set[str]:
    out: set[str] = set()
    for sub in ast.walk(tgt):
        if isinstance(sub, ast.Name):
            out.add(sub.id)
    return out


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------


def check(
    files: list[SourceFile],
    reporter: Reporter,
    config: AnalysisConfig,
    root: Path,
) -> None:
    # R5: engine-bypass dispatch — scans every analyzed file, not just
    # jit_paths, so it runs before the scope cut below
    if "jit-unbucketed-dispatch" in config.active_rules():
        _check_unbucketed_dispatch(files, reporter, config)

    scope_files = [sf for sf in files if _in_jit_paths(sf.rel, config)]
    if not scope_files:
        return
    index = _Index(scope_files)
    scope = [index.by_module[_module_name(sf.rel)] for sf in scope_files]

    # R3: static-arg hygiene at decoration and call sites
    for fi in scope:
        _check_static_hygiene(index, reporter, fi)

    # R1/R2: traced-context fixpoint from the jitted roots
    seen: set[tuple[tuple[str, str], frozenset[str]]] = set()
    queue: list[tuple[FuncRecord, frozenset[str]]] = []

    def enqueue(rec: FuncRecord, traced: frozenset[str]) -> None:
        key = (rec.key, traced)
        if key not in seen and _in_jit_paths(rec.sf.rel, config):
            seen.add(key)
            queue.append((rec, traced))

    for fi in scope:
        for rec in fi.funcs.values():
            if rec.is_jitted:
                traced = frozenset(set(rec.params) - rec.static_names)
                enqueue(rec, traced)
    while queue:
        rec, traced = queue.pop()
        fi = index.by_module[rec.module]
        walker = _TracedWalker(index, fi, reporter, enqueue)
        walker.walk_body(rec.node.body, set(traced))

    # R4: host dispatch syncs
    traced_fn_keys = {k for (k, _t) in seen}
    _compute_ret_dd(index, scope)
    for fi in scope:
        walker = _DispatchWalker(index, fi, reporter)
        for node in fi.sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                rec = fi.funcs.get(node.name)
                if rec is not None and (rec.is_jitted or rec.key in traced_fn_keys):
                    continue
                walker.walk_function(node, set())
            elif isinstance(node, ast.ClassDef):
                # two passes: first learn which self attrs hold device values
                self_dd: set[str] = set()
                for meth in node.body:
                    if isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        env: set[str] = set()
                        walker.walk_body_silent(meth.body, env)
                        self_dd |= {n for n in env if n.startswith("self.")}
                for meth in node.body:
                    if isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        walker.walk_function(meth, set(self_dd))
