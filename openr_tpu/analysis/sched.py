"""OPENR_SCHED — deterministic schedule exploration (a DPOR model checker).

The OPENR_TSAN detector (race.py) reports a race only when the OS scheduler
happens to produce the buggy interleaving, and the chaos fuzzer (chaos/fuzz.py)
searches *fault timelines*, not *thread schedules*.  This module closes the
gap loom/shuttle-style: small concurrency scenarios run on real threads, but
every thread is serialized onto a single controlled scheduler whose yield
points are exactly the seams race.py already hooks —

    =====================  ==========================================
    yield point            TSAN HB-edge it mirrors
    =====================  ==========================================
    thread.start / join    fork / join token
    lock.acquire/release   TsanLock release -> acquire edge
    queue.push/get/close   RWQueue per-item put -> get token
    eventbase.submit       run_in_event_base_thread handoff wrap
    future.set / get       Future resolve -> result token
    mem (scenario cp)      tracked-attribute access vocabulary
    =====================  ==========================================

At each yield point the running task *declares* its pending operation
(kind, resource, read/write) and parks; the controller therefore always
knows every enabled task's next op, which makes op independence computable
and sleep-set DPOR (Godefroid) sound: a schedule prefix is pruned exactly
when every enabled candidate is asleep, i.e. provably leads only to
interleavings equivalent to ones already explored.

Every explored schedule is a replayable ID (`scenario[+plant]:s<seed>:c0.c1...`,
the choice string normalized to indices into the sorted enabled-candidate
list).  Choices are interpreted tolerantly (`c mod len(candidates)`, first
candidate once exhausted), so *any* subsequence of a failing choice string is
itself a valid schedule — which is what lets the choice-prefix ddmin shrinker
(same skeleton as chaos.fuzz.shrink) minimize failures by chunk removal.

Zero-overhead-off discipline matches OPENR_TSAN: the runtime seams read the
module constant ``SCHED`` (None unless a controller is mid-run) and branch on
``is not None``; no scheduler objects exist otherwise.  Arm exploration with
``OPENR_SCHED=1`` or ``python -m openr_tpu.analysis --sched``.

This module must never import jax (analysis-package contract) and imports the
runtime lazily inside scenario builders to avoid import cycles with
runtime/queue.py, which imports us for its seams.
"""

from __future__ import annotations

import concurrent.futures
import hashlib
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from . import race as _race

# ---------------------------------------------------------------------------
# arming (zero-overhead-off: seams read this one module constant)
# ---------------------------------------------------------------------------

# The active controller while a single schedule executes; None otherwise.
# Runtime seams (queue.py, eventbase.py, serving/) do a late-bound
# ``_sched.SCHED`` read and branch on ``is not None`` — one module-attribute
# load per seam when disarmed, exactly the TSAN standard.
SCHED: Optional["SchedController"] = None

_ENV_ARMED = os.environ.get("OPENR_SCHED", "") == "1"


def env_armed() -> bool:
    """True when OPENR_SCHED=1 was set at import (CLI implies --sched)."""
    return _ENV_ARMED


def budget_s(default: float = 20.0) -> float:
    """Session wall budget: OPENR_SCHED_BUDGET_S, else `default` seconds."""
    raw = os.environ.get("OPENR_SCHED_BUDGET_S", "")
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


# ---------------------------------------------------------------------------
# counters (sched.* family; pre-seeded zeros, wired as the ctrl handler's
# `sched` module so the family answers getCounters on both wire surfaces
# before any exploration ever runs — same contract as chaos.fuzz)
# ---------------------------------------------------------------------------

SCHED_COUNTER_KEYS = (
    "sched.schedules_explored",
    "sched.dpor_prunes",
    "sched.replays",
    "sched.shrinks",
    "sched.planted_finds",
)


class SchedCounters:
    """Pre-seeded ``sched.*`` registry (module-level singleton below)."""

    def __init__(self) -> None:
        self.counters: dict[str, int] = {k: 0 for k in SCHED_COUNTER_KEYS}

    def get_counters(self) -> dict[str, int]:
        return dict(self.counters)

    def _bump(self, name: str, delta: int = 1) -> None:
        # underscore spelling: the counter-unbumped static rule recognizes
        # `*._bump("literal")` call sites (chaos.fuzz's `.bump` lives in an
        # analysis-excluded tree; this file is analyzed)
        self.counters[name] = self.counters.get(name, 0) + delta

    # public alias, API parity with chaos.fuzz.FuzzCounters
    bump = _bump


SCHED_COUNTERS = SchedCounters()


class SchedInfraError(RuntimeError):
    """Checker-infrastructure failure (leaked thread, internal protocol
    violation) — maps to CLI exit 2, never to a finding."""


class _SchedAbort(BaseException):
    """Raised inside parked tasks to unwind them at run teardown; never a
    finding.  BaseException so scenario `except Exception` can't eat it."""


# ---------------------------------------------------------------------------
# pending-op vocabulary + independence (the DPOR side of the HB-edge table)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PendingOp:
    kind: str  # "queue.push" | "queue.get" | "queue.close" | "lock.acquire"
    #            | "lock.release" | "future.set" | "future.get" | "mem"
    #            | "eventbase.submit" | "thread.start" | "thread.join" | "begin"
    resource: str  # stable per-run label, e.g. "q:1", "lock:ledger", "fut:2"
    write: bool = True

    def sig(self) -> str:
        return f"{self.kind}({self.resource}{',w' if self.write else ',r'})"


def ops_dependent(a: PendingOp, b: PendingOp) -> bool:
    """Two ops commute unless they touch the same resource and at least one
    writes — the same vocabulary the TSAN detector derives HB edges from."""
    if a.kind == "begin" or b.kind == "begin":
        return False
    if a.resource != b.resource:
        return False
    return a.write or b.write


# ---------------------------------------------------------------------------
# controller: real threads, one token
# ---------------------------------------------------------------------------


class _Task:
    __slots__ = (
        "idx",
        "name",
        "fn",
        "thread",
        "go",
        "pending",
        "enabled_fn",
        "parked",
        "done",
        "error",
        "abort",
    )

    def __init__(self, idx: int, name: str, fn: Callable[[], Any]) -> None:
        self.idx = idx
        self.name = name
        self.fn = fn
        self.thread: Optional[threading.Thread] = None
        self.go = threading.Event()
        self.pending: Optional[PendingOp] = None
        self.enabled_fn: Optional[Callable[[], bool]] = None
        self.parked = False
        self.done = False
        self.error: Optional[BaseException] = None
        self.abort = False


class SchedController:
    """Serializes registered tasks onto one grant token.

    Tasks park at yield points after declaring their pending op; the run
    loop (driver thread) picks one enabled parked task per step via the
    policy, grants it, and waits for quiescence.  Threads the controller
    did not register (pytest's main thread, module daemons) pass through
    every seam untouched.
    """

    MAX_STEPS = 2000

    def __init__(self, decide: Callable[[list[tuple[int, PendingOp]]], int],
                 note_step: Optional[Callable[[list[tuple[int, PendingOp]], int], None]] = None,
                 max_steps: Optional[int] = None) -> None:
        self._decide = decide
        self._note_step = note_step
        self._tasks: list[_Task] = []
        self._tls = threading.local()
        self._mon = threading.Condition()
        self._labels: dict[int, str] = {}
        self._label_counts: dict[str, int] = {}
        self._keep: list[Any] = []  # pin labelled objects for the run
        self.max_steps = max_steps or self.MAX_STEPS
        self.steps = 0
        self.choices: list[int] = []  # normalized (only multi-candidate points)
        self.trace: list[tuple[str, str, str]] = []  # (task, kind, resource)
        self.failures: list[str] = []
        self.pruned = False
        self._aborting = False

    # -- registration (driver thread, before run) ---------------------------

    def add_task(self, name: str, fn: Callable[[], Any]) -> None:
        self._tasks.append(_Task(len(self._tasks), name, fn))

    def _label(self, obj: Any, prefix: str) -> str:
        lab = self._labels.get(id(obj))
        if lab is None:
            n = self._label_counts.get(prefix, 0) + 1
            self._label_counts[prefix] = n
            lab = f"{prefix}:{n}"
            self._labels[id(obj)] = lab
            self._keep.append(obj)
        return lab

    # -- task-side protocol -------------------------------------------------

    def _cur(self) -> Optional[_Task]:
        return getattr(self._tls, "task", None)

    def _yield(self, t: _Task, op: PendingOp,
               enabled: Optional[Callable[[], bool]] = None) -> None:
        t.pending = op
        t.enabled_fn = enabled
        t.go.clear()
        # abort handshake: _abort_parked sets t.abort BEFORE t.go.set(), so
        # either the clear above erased a set we can still observe via
        # t.abort here, or the set lands after and go.wait() sees it sticky
        if self._aborting or t.abort:
            raise _SchedAbort()
        with self._mon:
            t.parked = True
            self._mon.notify_all()
        t.go.wait()
        if self._aborting or t.abort:
            raise _SchedAbort()
        t.pending = None
        t.enabled_fn = None

    def _task_body(self, t: _Task) -> None:
        self._tls.task = t
        try:
            # initial park: "begin" is independent of everything, so DPOR
            # never wastes schedules permuting pure task starts
            self._yield(t, PendingOp("begin", f"task:{t.idx}", False))
            t.fn()
        except _SchedAbort:
            pass
        except BaseException as e:  # noqa: BLE001 — any escape is a finding
            t.error = e
        finally:
            with self._mon:
                t.done = True
                t.parked = False
                self._mon.notify_all()

    # -- seam API (called from runtime modules through the SCHED constant) --

    def controls_current_thread(self) -> bool:
        return self._cur() is not None

    def queue_op(self, q: Any, kind: str) -> None:
        """Non-blocking queue op (push / try_get / close): one yield point."""
        t = self._cur()
        if t is None:
            return
        self._yield(t, PendingOp(kind, self._label(q, "q"), True))

    def queue_get_gate(self, q: Any, ready: Callable[[], bool]) -> bool:
        """Blocking-get gate: park until an item is available or the queue
        is closed.  Returns True iff the calling thread is controlled —
        the caller must then take its non-blocking pop path (the real
        cond.wait would block the whole serialized world)."""
        t = self._cur()
        if t is None:
            return False
        self._yield(t, PendingOp("queue.get", self._label(q, "q"), True),
                    enabled=ready)
        return True

    def handoff(self, eb: Any) -> None:
        """Eventbase cross-thread submit (run_in_event_base_thread /
        add_fiber_task / schedule_timeout marshalling)."""
        t = self._cur()
        if t is None:
            return
        self._yield(t, PendingOp("eventbase.submit", self._label(eb, "eb"), True))

    def region(self, point: str) -> None:
        """Named interleaving-sensitive region in product code (serving
        admission, ledger close): a plain mem-write yield point."""
        t = self._cur()
        if t is None:
            return
        self._yield(t, PendingOp("mem", f"mem:{point}", True))

    def mem(self, resource: str, write: bool = True) -> None:
        """Scenario checkpoint: declare the next shared-memory access."""
        t = self._cur()
        if t is None:
            return
        self._yield(t, PendingOp("mem", f"mem:{resource}", write))

    def future_set(self, fut: Any) -> None:
        t = self._cur()
        if t is None:
            return
        self._yield(t, PendingOp("future.set", self._label(fut, "fut"), True))

    def future_get_gate(self, fut: Any) -> bool:
        t = self._cur()
        if t is None:
            return False
        self._yield(t, PendingOp("future.get", self._label(fut, "fut"), False),
                    enabled=fut.done)
        return True

    def thread_start(self, th: Any) -> None:
        t = self._cur()
        if t is None:
            return
        self._yield(t, PendingOp("thread.start", self._label(th, "th"), True))

    def thread_join_gate(self, th: Any) -> bool:
        t = self._cur()
        if t is None:
            return False
        self._yield(t, PendingOp("thread.join", self._label(th, "th"), False),
                    enabled=lambda: not th.is_alive())
        return True

    # -- driver-side run loop ----------------------------------------------

    def _wait_quiescent(self) -> None:
        deadline = time.monotonic() + 30.0
        with self._mon:
            while not all(t.parked or t.done for t in self._tasks):
                if not self._mon.wait(timeout=1.0) and time.monotonic() > deadline:
                    raise SchedInfraError(
                        "controller hang: a task neither parked nor exited "
                        "(blocking call outside the seam vocabulary?)"
                    )

    def _enabled(self, t: _Task) -> bool:
        if t.enabled_fn is None:
            return True
        try:
            return bool(t.enabled_fn())
        except Exception:  # noqa: BLE001 — let the op itself raise on grant
            return True

    def _abort_parked(self) -> None:
        self._aborting = True
        for t in self._tasks:
            if not t.done:
                t.abort = True
                t.go.set()
        for t in self._tasks:
            if t.thread is not None:
                t.thread.join(timeout=5.0)
                if t.thread.is_alive():
                    raise SchedInfraError(f"leaked task thread: {t.name}")

    def run(self) -> None:
        global SCHED
        if SCHED is not None:
            raise SchedInfraError("nested schedule execution")
        SCHED = self
        error: Optional[BaseException] = None
        try:
            for t in self._tasks:
                t.thread = threading.Thread(
                    target=self._task_body, args=(t,),
                    name=f"sched-{t.name}", daemon=True,
                )
                t.thread.start()
            self._wait_quiescent()
            while True:
                live = [t for t in self._tasks if not t.done]
                if not live:
                    break
                enabled = [t for t in live if t.parked and self._enabled(t)]
                if not enabled:
                    waiting = ", ".join(
                        f"{t.name}@{t.pending.sig() if t.pending else '?'}"
                        for t in live
                    )
                    self.failures.append(f"deadlock: all tasks blocked [{waiting}]")
                    break
                self.steps += 1
                if self.steps > self.max_steps:
                    self.failures.append(
                        f"livelock: step budget ({self.max_steps}) exceeded"
                    )
                    break
                ops = [(t.idx, t.pending) for t in enabled]
                k = self._decide(ops)
                if k < 0:  # policy pruned this branch (sleep-set redundant)
                    self.pruned = True
                    break
                if len(ops) >= 2:
                    self.choices.append(k)
                chosen = enabled[k]
                op = chosen.pending
                self.trace.append((chosen.name, op.kind, op.resource))
                if self._note_step is not None:
                    self._note_step(ops, k)
                with self._mon:
                    chosen.parked = False
                chosen.go.set()
                self._wait_quiescent()
        except BaseException as e:  # noqa: BLE001 — re-raised below
            error = e
        SCHED = None
        try:
            self._abort_parked()
        except SchedInfraError as e:
            if error is None:
                error = e
        if error is not None:
            raise error
        for t in self._tasks:
            if t.error is not None:
                self.failures.append(f"exception: {t.name}: {t.error!r}")
        det = _race.TSAN
        if det is not None:
            for finding in det.drain():
                self.failures.append(f"race: {finding}")


class SchedLock:
    """Scenario lock with the TsanLock seam vocabulary: acquire parks until
    the lock is free (enabledness, never a real block), release is its own
    yield point, so a task can park *while holding* the lock and the
    explorer sees every critical-section interleaving."""

    def __init__(self, controller: SchedController, name: str) -> None:
        self._c = controller
        self._labelname = f"lock:{name}"
        self._owner: Optional[_Task] = None

    def acquire(self) -> None:
        t = self._c._cur()
        if t is None:  # driver-side (build/check): serialized, just take it
            self._owner = None
            return
        self._c._yield(t, PendingOp("lock.acquire", self._labelname, True),
                       enabled=lambda: self._owner is None)
        self._owner = t

    def release(self) -> None:
        t = self._c._cur()
        if t is None:
            self._owner = None
            return
        self._c._yield(t, PendingOp("lock.release", self._labelname, True))
        self._owner = None

    def __enter__(self) -> "SchedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()


# ---------------------------------------------------------------------------
# runtime patches (Future resolve/await, Thread start/join) — installed only
# while a session runs, refcounted, restored on exit; race.py discipline
# ---------------------------------------------------------------------------

_SAVED: dict[str, Any] = {}
_PATCH_DEPTH = 0
_PATCH_LOCK = threading.Lock()


def _patched_set_result(self, value):  # type: ignore[no-untyped-def]
    sc = SCHED
    if sc is not None:
        sc.future_set(self)
    return _SAVED["future.set_result"](self, value)


def _patched_set_exception(self, exc):  # type: ignore[no-untyped-def]
    sc = SCHED
    if sc is not None:
        sc.future_set(self)
    return _SAVED["future.set_exception"](self, exc)


def _patched_result(self, timeout=None):  # type: ignore[no-untyped-def]
    sc = SCHED
    if sc is not None and sc.future_get_gate(self):
        return _SAVED["future.result"](self, 0)
    return _SAVED["future.result"](self, timeout)


def _patched_exception(self, timeout=None):  # type: ignore[no-untyped-def]
    sc = SCHED
    if sc is not None and sc.future_get_gate(self):
        return _SAVED["future.exception"](self, 0)
    return _SAVED["future.exception"](self, timeout)


def _patched_thread_start(self):  # type: ignore[no-untyped-def]
    sc = SCHED
    if sc is not None:
        sc.thread_start(self)
    return _SAVED["thread.start"](self)


def _patched_thread_join(self, timeout=None):  # type: ignore[no-untyped-def]
    sc = SCHED
    if sc is not None and sc.thread_join_gate(self):
        return _SAVED["thread.join"](self, 0)
    return _SAVED["thread.join"](self, timeout)


def _install_patches() -> None:
    global _PATCH_DEPTH
    with _PATCH_LOCK:
        _PATCH_DEPTH += 1
        if _PATCH_DEPTH > 1:
            return
        fut = concurrent.futures.Future
        _SAVED["future.set_result"] = fut.set_result
        _SAVED["future.set_exception"] = fut.set_exception
        _SAVED["future.result"] = fut.result
        _SAVED["future.exception"] = fut.exception
        _SAVED["thread.start"] = threading.Thread.start
        _SAVED["thread.join"] = threading.Thread.join
        fut.set_result = _patched_set_result  # type: ignore[method-assign]
        fut.set_exception = _patched_set_exception  # type: ignore[method-assign]
        fut.result = _patched_result  # type: ignore[method-assign]
        fut.exception = _patched_exception  # type: ignore[method-assign]
        threading.Thread.start = _patched_thread_start  # type: ignore[method-assign]
        threading.Thread.join = _patched_thread_join  # type: ignore[method-assign]


def _remove_patches() -> None:
    global _PATCH_DEPTH
    with _PATCH_LOCK:
        _PATCH_DEPTH -= 1
        if _PATCH_DEPTH > 0:
            return
        fut = concurrent.futures.Future
        fut.set_result = _SAVED.pop("future.set_result")  # type: ignore[method-assign]
        fut.set_exception = _SAVED.pop("future.set_exception")  # type: ignore[method-assign]
        fut.result = _SAVED.pop("future.result")  # type: ignore[method-assign]
        fut.exception = _SAVED.pop("future.exception")  # type: ignore[method-assign]
        threading.Thread.start = _SAVED.pop("thread.start")  # type: ignore[method-assign]
        threading.Thread.join = _SAVED.pop("thread.join")  # type: ignore[method-assign]


def patches_installed() -> bool:
    return _PATCH_DEPTH > 0


# ---------------------------------------------------------------------------
# scheduling policies
# ---------------------------------------------------------------------------


class _ReplayPolicy:
    """Tolerant choice-string interpretation: the i-th *multi-candidate*
    decision point consumes choices[i] mod len(candidates); exhausted
    choices fall back to the first candidate.  Any subsequence of a valid
    choice string is therefore itself a valid schedule (ddmin fuel)."""

    def __init__(self, choices: list[int]) -> None:
        self._choices = choices
        self._ci = 0

    def decide(self, ops: list[tuple[int, PendingOp]]) -> int:
        if len(ops) < 2:
            return 0
        if self._ci < len(self._choices):
            k = self._choices[self._ci] % len(ops)
            self._ci += 1
            return k
        return 0


class _RandomPolicy:
    """Uniform random walk over enabled candidates (seeded)."""

    def __init__(self, rng: random.Random) -> None:
        self._rng = rng

    def decide(self, ops: list[tuple[int, PendingOp]]) -> int:
        return 0 if len(ops) < 2 else self._rng.randrange(len(ops))


class _POSPolicy:
    """Partial-order sampling: random task priorities; after each executed
    op, every candidate whose pending op is dependent with it gets a fresh
    priority.  Covers racy pairs far better than the uniform walk."""

    def __init__(self, rng: random.Random) -> None:
        self._rng = rng
        self._prio: dict[int, float] = {}

    def _p(self, idx: int) -> float:
        if idx not in self._prio:
            self._prio[idx] = self._rng.random()
        return self._prio[idx]

    def decide(self, ops: list[tuple[int, PendingOp]]) -> int:
        if len(ops) < 2:
            return 0
        best = max(range(len(ops)), key=lambda j: self._p(ops[j][0]))
        return best

    def note_step(self, ops: list[tuple[int, PendingOp]], k: int) -> None:
        executed = ops[k][1]
        for idx, op in ops:
            if idx != ops[k][0] and ops_dependent(op, executed):
                self._prio[idx] = self._rng.random()


class _ExplorerPolicy:
    """Sleep-set DPOR node executor.

    Replays a forced choice prefix (the node address), then continues
    first-awake, generating one backtrack point per awake sibling at every
    multi-candidate step, each with the sleep set the sleep-set algorithm
    prescribes: Z(child_d) = {u in sleep ∪ done-siblings | op(u) indep op(d)}.
    If every enabled candidate is asleep the whole branch is provably
    redundant and the run aborts (decide -> -1)."""

    def __init__(self, forced: list[int], entry_sleep: dict[int, PendingOp],
                 indep: Callable[[PendingOp, PendingOp], bool]) -> None:
        self._forced = forced
        self._entry_sleep = entry_sleep
        self._indep = indep
        self._ci = 0
        self._choices: list[int] = []  # normalized, mirrors controller
        self._sleep: Optional[dict[int, PendingOp]] = (
            dict(entry_sleep) if not forced else None
        )
        self.branch_points: list[tuple[list[int], dict[int, PendingOp]]] = []
        self.sleep_skips = 0  # enabled-but-sleeping candidates skipped

    def decide(self, ops: list[tuple[int, PendingOp]]) -> int:
        multi = len(ops) >= 2
        if self._sleep is None:  # still replaying the forced prefix
            if not multi:
                return 0
            k = self._forced[self._ci] % len(ops)
            self._ci += 1
            if self._ci == len(self._forced):
                pass  # sleep activates in note_step after this op executes
            self._choices.append(k)
            return k
        awake = [j for j, (idx, _op) in enumerate(ops) if idx not in self._sleep]
        if not awake:
            return -1  # sleep-set prune: subtree redundant
        k = awake[0]
        if multi:
            self.sleep_skips += len(ops) - len(awake)
            done: list[tuple[int, PendingOp]] = [ops[k]]
            for j in awake[1:]:
                idx_j, op_j = ops[j]
                base = dict(self._sleep)
                for didx, dop in done:
                    base[didx] = dop
                child_sleep = {
                    u: uop for u, uop in base.items() if self._indep(uop, op_j)
                }
                self.branch_points.append((self._choices + [j], child_sleep))
                done.append(ops[j])
            self._choices.append(k)
        return k

    def note_step(self, ops: list[tuple[int, PendingOp]], k: int) -> None:
        executed = ops[k][1]
        if self._sleep is None:
            if self._ci == len(self._forced):
                # the last forced choice just executed: enter explore mode
                # with the sleep set the parent computed for this node
                self._sleep = dict(self._entry_sleep)
            return
        # wake every sleeper whose op is dependent with the executed op
        self._sleep = {
            u: uop for u, uop in self._sleep.items()
            if self._indep(uop, executed)
        }


# ---------------------------------------------------------------------------
# scenario library
# ---------------------------------------------------------------------------


class SchedWorld:
    """Scenario construction surface: tasks, seam-aware primitives, and the
    `cp()` checkpoint that declares a shared-memory access as a yield point
    (the tracked-attribute analog of race.py's __setattr__ hook)."""

    def __init__(self, controller: SchedController) -> None:
        self._c = controller
        self.state: dict[str, Any] = {}

    def task(self, name: str, fn: Callable[[], Any]) -> None:
        self._c.add_task(name, fn)

    def lock(self, name: str = "L") -> SchedLock:
        return SchedLock(self._c, name)

    def queue(self, maxlen: Optional[int] = None,
              on_shed: Optional[Callable[[Any], None]] = None) -> Any:
        from ..runtime.queue import RWQueue  # lazy: queue.py imports us

        return RWQueue(maxlen=maxlen, on_shed=on_shed)

    def future(self) -> "concurrent.futures.Future[Any]":
        return concurrent.futures.Future()

    def cp(self, resource: str, write: bool = True) -> None:
        self._c.mem(resource, write)


@dataclass
class Scenario:
    name: str
    build: Callable[[SchedWorld, bool], Callable[[], list[str]]]
    plantable: bool = False


SCENARIOS: dict[str, Scenario] = {}

# The two structurally smallest scenarios: explored exhaustively (with an
# exhaustiveness certificate) at tier-1 budget; the rest are sampled.
EXHAUSTIVE_SCENARIOS = ("router_hedge_vs_death", "queue_shed_vs_carry")


def _scenario(name: str, plantable: bool = False):
    def deco(build: Callable[[SchedWorld, bool], Callable[[], list[str]]]):
        SCENARIOS[name] = Scenario(name, build, plantable)
        return build

    return deco


@_scenario("coalescer_fanin")
def _sc_coalescer_fanin(world: SchedWorld, plant: bool):
    """Coalescer fan-in vs flap invalidation: two flapping producers write
    truth then notify; the coalescer must re-read truth per notification
    (apply-latest), never the stale value captured at notify time."""
    q = world.queue()
    truth: dict[str, int] = {}
    applied: dict[str, int] = {}
    consumed: list[str] = []

    def flapper(val: int) -> Callable[[], None]:
        def run() -> None:
            world.cp("truth", write=True)
            truth["a"] = val
            q.push("a")

        return run

    def coalescer() -> None:
        for _ in range(2):
            key = q.get()
            world.cp("truth", write=False)
            applied[key] = truth[key]
            consumed.append(key)

    world.task("flap1", flapper(1))
    world.task("flap2", flapper(2))
    world.task("coalescer", coalescer)

    def check() -> list[str]:
        fails = []
        if len(consumed) != 2:
            fails.append(f"lost-notification: consumed {len(consumed)}/2")
        if applied.get("a") != truth.get("a"):
            fails.append(
                f"stale-apply: applied={applied.get('a')} truth={truth.get('a')}"
            )
        return fails

    return check


@_scenario("queue_shed_vs_carry")
def _sc_queue_shed_vs_carry(world: SchedWorld, plant: bool):
    """Bounded-queue shed vs per-item carry: drop-oldest overflow must
    conserve items (received + shed == pushed) and preserve order."""
    from ..runtime.queue import QueueClosedError  # lazy

    shed: list[int] = []
    received: list[int] = []
    q = world.queue(maxlen=1, on_shed=shed.append)

    def producer() -> None:
        for i in range(3):
            q.push(i)
        q.close()

    def consumer() -> None:
        while True:
            try:
                received.append(q.get())
            except QueueClosedError:
                return

    world.task("producer", producer)
    world.task("consumer", consumer)

    def check() -> list[str]:
        fails = []
        if sorted(received + shed) != [0, 1, 2]:
            fails.append(f"silent-drop: received={received} shed={shed}")
        if received != sorted(received):
            fails.append(f"reorder: received={received}")
        return fails

    return check


@_scenario("router_hedge_vs_death", plantable=True)
def _sc_router_hedge_vs_death(world: SchedWorld, plant: bool):
    """Router hedge vs replica death: two completion paths (primary reply,
    hedged replica dying) both close the dispatch ledger.  The planted
    variant drops the ledger lock, exposing the classic read-modify-write
    lost update the explorer must find, shrink, and replay."""
    ledger = {"submitted": 2, "replied": 0}
    lock = world.lock("ledger")
    fut_primary = world.future()
    fut_hedge = world.future()

    def completion(fut: Any, ok: bool) -> Callable[[], None]:
        def close_ledger() -> None:
            world.cp("ledger", write=False)
            r = ledger["replied"]
            world.cp("ledger", write=True)
            ledger["replied"] = r + 1

        def run() -> None:
            if plant:
                close_ledger()  # planted: unlocked read-modify-write
            else:
                with lock:
                    close_ledger()
            if ok:
                fut.set_result("reply")
            else:
                fut.set_exception(RuntimeError("replica died"))

        return run

    world.task("primary", completion(fut_primary, True))
    world.task("death", completion(fut_hedge, False))

    def check() -> list[str]:
        fails = []
        if ledger["replied"] != ledger["submitted"]:
            fails.append(
                "ledger-lost-update: replied="
                f"{ledger['replied']} submitted={ledger['submitted']}"
            )
        if not (fut_primary.done() and fut_hedge.done()):
            fails.append("unresolved-future")
        return fails

    return check


@_scenario("delta_order_vs_demotion")
def _sc_delta_order_vs_demotion(world: SchedWorld, plant: bool):
    """Delta-coalescer ordering vs full-rebuild demotion: incremental
    deltas apply monotonically; a full rebuild snapshots truth.  FIFO
    consumption must leave the view at truth no matter how the demotion
    interleaves with in-flight deltas."""
    q = world.queue()
    truth = {"ver": 0}
    view = {"ver": 0}

    def producer() -> None:
        for v in (1, 2):
            world.cp("truth", write=True)
            truth["ver"] = v
            q.push(("delta", v))

    def demoter() -> None:
        q.push(("full", None))

    def consumer() -> None:
        for _ in range(3):
            kind, v = q.get()
            if kind == "delta":
                world.cp("view", write=True)
                if v > view["ver"]:
                    view["ver"] = v
            else:
                world.cp("truth", write=False)
                world.cp("view", write=True)
                view["ver"] = truth["ver"]

    world.task("producer", producer)
    world.task("demoter", demoter)
    world.task("consumer", consumer)

    def check() -> list[str]:
        if view["ver"] != truth["ver"]:
            return [f"demotion-regressed-view: view={view['ver']} truth={truth['ver']}"]
        return []

    return check


@_scenario("eventbase_stop_vs_timeout")
def _sc_eventbase_stop_vs_timeout(world: SchedWorld, plant: bool):
    """Eventbase stop vs pending timeout: the loop drains its callback
    queue on close (queue close-drains), and a submit that loses the race
    with stop must account the callback cancelled — never silently drop."""
    from ..runtime.queue import QueueClosedError  # lazy

    cbq = world.queue()
    ran: list[str] = []
    cancelled: list[str] = []
    fired = world.future()

    def loop() -> None:
        while True:
            try:
                fn = cbq.get()
            except QueueClosedError:
                return
            fn()

    def submitter() -> None:
        def timeout_cb() -> None:
            ran.append("timeout")
            fired.set_result(True)

        if not cbq.push(timeout_cb):
            cancelled.append("timeout")
            fired.set_exception(RuntimeError("eventbase stopped"))

    def stopper() -> None:
        cbq.close()

    world.task("loop", loop)
    world.task("submitter", submitter)
    world.task("stopper", stopper)

    def check() -> list[str]:
        fails = []
        if not fired.done():
            fails.append("silent-drop: timeout neither fired nor cancelled")
        if len(ran) + len(cancelled) != 1:
            fails.append(f"double-account: ran={ran} cancelled={cancelled}")
        return fails

    return check


@_scenario("kvstore_merge_vs_ttl")
def _sc_kvstore_merge_vs_ttl(world: SchedWorld, plant: bool):
    """KvStore merge vs TTL expiry, driving the real CRDT merge: expiry
    captures a generation, re-validates under the lock before deleting —
    a newer merged value must never be killed by a stale expiry."""
    from ..kvstore.kvstore import merge_key_values  # lazy
    from ..types import Value  # lazy

    store = {"k": Value(version=1, originator_id="n1", value=b"v1")}
    lock = world.lock("store")
    accepted: dict[str, Value] = {}
    expiry = {"captured": None, "deleted": False}

    def merger() -> None:
        with lock:
            world.cp("store", write=True)
            delta = merge_key_values(
                store, {"k": Value(version=2, originator_id="n1", value=b"v2")}
            )
            accepted.update(delta)

    def expirer() -> None:
        with lock:
            world.cp("store", write=False)
            snap = store.get("k")
            gen = (snap.version, snap.ttl_version) if snap else None
        expiry["captured"] = gen
        # the expiry decision and the delete are separate critical
        # sections: the merge may land in between (the race under test)
        with lock:
            world.cp("store", write=True)
            cur = store.get("k")
            if cur is not None and gen == (cur.version, cur.ttl_version):
                del store["k"]
                expiry["deleted"] = True

    world.task("merger", merger)
    world.task("expirer", expirer)

    def check() -> list[str]:
        fails = []
        if accepted.get("k") is None or accepted["k"].version != 2:
            fails.append(f"merge-rejected: accepted={accepted}")
        if "k" not in store and expiry["captured"] == (1, 0):
            fails.append("stale-expiry-killed-newer: v2 deleted by v1 expiry")
        return fails

    return check


@_scenario("engine_rewire_vs_sync")
def _sc_engine_rewire_vs_sync(world: SchedWorld, plant: bool):
    """Engine rewire-chain replay vs concurrent sync: sync validates its
    snapshot with an epoch re-read (seqlock discipline); a torn snapshot
    (chain length disagreeing with the epoch) is the finding."""
    chain: list[tuple[str, int]] = []
    epoch = {"n": 0}
    lock = world.lock("engine")
    snaps: list[tuple[int, int]] = []

    def rewire() -> None:
        for i in range(2):
            with lock:
                world.cp("engine", write=True)
                chain.append(("rewire", i))
                epoch["n"] += 1

    def sync() -> None:
        for _ in range(3):
            with lock:
                world.cp("engine", write=False)
                e1 = epoch["n"]
                replayed = len(chain)
            with lock:
                world.cp("engine", write=False)
                e2 = epoch["n"]
            if e1 == e2:
                snaps.append((e1, replayed))
                return
        with lock:
            world.cp("engine", write=False)
            snaps.append((epoch["n"], len(chain)))

    world.task("rewire", rewire)
    world.task("sync", sync)

    def check() -> list[str]:
        fails = []
        if not snaps:
            fails.append("sync-never-completed")
        elif snaps[-1][0] != snaps[-1][1]:
            fails.append(f"torn-snapshot: epoch={snaps[-1][0]} replayed={snaps[-1][1]}")
        if epoch["n"] != 2 or len(chain) != 2:
            fails.append(f"lost-rewire: epoch={epoch['n']} chain={len(chain)}")
        return fails

    return check


@_scenario("sched_shutdown_vs_future")
def _sc_sched_shutdown_vs_future(world: SchedWorld, plant: bool):
    """Scheduler shutdown vs in-flight future: the admission check and the
    enqueue race with the stop latch; whichever way it lands, the caller's
    future must resolve exactly once (reply or shed) — never hang."""
    from ..runtime.queue import QueueClosedError  # lazy

    q = world.queue()
    flags = {"accepting": True}
    fut = world.future()

    def worker() -> None:
        while True:
            try:
                f = q.get()
            except QueueClosedError:
                return
            f.set_result("ok")

    def submitter() -> None:
        world.cp("accepting", write=False)
        if flags["accepting"]:
            if not q.push(fut):
                fut.set_exception(RuntimeError("shed: queue closed"))
        else:
            fut.set_exception(RuntimeError("shed: draining"))

    def stopper() -> None:
        world.cp("accepting", write=True)
        flags["accepting"] = False
        q.close()

    world.task("worker", worker)
    world.task("submitter", submitter)
    world.task("stopper", stopper)

    def check() -> list[str]:
        if not fut.done():
            return ["hung-future: submit neither replied nor shed"]
        return []

    return check


# ---------------------------------------------------------------------------
# execution, replay IDs, exploration, shrinking
# ---------------------------------------------------------------------------


@dataclass
class RunResult:
    scenario: str
    plant: bool
    choices: list[int]
    trace: tuple[tuple[str, str, str], ...]
    failures: list[str]
    pruned: bool
    steps: int

    def trace_fingerprint(self) -> str:
        h = hashlib.sha1(repr(self.trace).encode()).hexdigest()
        return h[:12]


def choice_fingerprint(scenario: str, choices: list[int]) -> str:
    raw = f"{scenario}:{'.'.join(map(str, choices))}"
    return hashlib.sha1(raw.encode()).hexdigest()[:10]


def format_schedule_id(scenario: str, seed: int, choices: list[int],
                       plant: bool = False) -> str:
    name = f"{scenario}+plant" if plant else scenario
    body = ".".join(map(str, choices)) if choices else "-"
    return f"{name}:s{seed}:{body}"


def parse_schedule_id(sid: str) -> tuple[str, bool, int, list[int]]:
    try:
        name, seed_s, body = sid.split(":", 2)
        plant = name.endswith("+plant")
        if plant:
            name = name[: -len("+plant")]
        seed = int(seed_s.lstrip("s"))
        choices = [] if body == "-" else [int(c) for c in body.split(".")]
    except (ValueError, AttributeError) as e:
        raise ValueError(f"malformed schedule id {sid!r}") from e
    if name not in SCENARIOS:
        raise ValueError(f"unknown scenario in schedule id: {name!r}")
    return name, plant, seed, choices


def _execute(scenario: str, plant: bool, policy: Any,
             max_steps: Optional[int] = None) -> RunResult:
    sc = SCENARIOS[scenario]
    _install_patches()
    try:
        controller = SchedController(
            policy.decide, getattr(policy, "note_step", None), max_steps
        )
        world = SchedWorld(controller)
        check = sc.build(world, plant)
        if not controller._tasks:
            raise SchedInfraError(f"scenario {scenario} registered no tasks")
        controller.run()
        failures = list(controller.failures)
        if not controller.pruned:
            failures.extend(check())
        return RunResult(
            scenario=scenario,
            plant=plant,
            choices=list(controller.choices),
            trace=tuple(controller.trace),
            failures=failures,
            pruned=controller.pruned,
            steps=controller.steps,
        )
    finally:
        _remove_patches()


def run_schedule(scenario: str, choices: list[int],
                 plant: bool = False) -> RunResult:
    """Execute one schedule from its normalized choice string."""
    return _execute(scenario, plant, _ReplayPolicy(list(choices)))


def replay_schedule(sid: str) -> RunResult:
    """Replay a schedule ID bit-identically (same choices -> same trace)."""
    scenario, plant, _seed, choices = parse_schedule_id(sid)
    SCHED_COUNTERS._bump("sched.replays")
    return run_schedule(scenario, choices, plant)


@dataclass
class ScheduleFailure:
    schedule_id: str
    choices: list[int]
    failures: list[str]
    trace_fingerprint: str


@dataclass
class ExploreResult:
    scenario: str
    plant: bool
    seed: int
    mode: str  # "dpor" | "naive" | "random" | "pos"
    schedules: int = 0
    prunes: int = 0
    complete: bool = False
    failures: list[ScheduleFailure] = field(default_factory=list)
    coverage_tokens: set[str] = field(default_factory=set)
    elapsed_s: float = 0.0


def explore(scenario: str, *, plant: bool = False, seed: int = 0,
            mode: str = "dpor", max_schedules: int = 5000,
            wall_budget_s: float = 30.0, max_failures: int = 10) -> ExploreResult:
    """Systematically (dpor/naive) or stochastically (random/pos) explore a
    scenario's interleavings.  `complete=True` is the exhaustiveness
    certificate: the DPOR (or naive) frontier drained within budget."""
    if scenario not in SCENARIOS:
        raise SchedInfraError(f"unknown scenario: {scenario}")
    res = ExploreResult(scenario=scenario, plant=plant, seed=seed, mode=mode)
    t0 = time.monotonic()

    def out_of_budget() -> bool:
        return (
            res.schedules >= max_schedules
            or time.monotonic() - t0 > wall_budget_s
        )

    def record(run: RunResult) -> None:
        res.schedules += 1
        SCHED_COUNTERS._bump("sched.schedules_explored")
        res.coverage_tokens.add(
            f"sched:{scenario}:{choice_fingerprint(scenario, run.choices)}"
        )
        if run.failures and len(res.failures) < max_failures:
            res.failures.append(
                ScheduleFailure(
                    schedule_id=format_schedule_id(scenario, seed, run.choices, plant),
                    choices=list(run.choices),
                    failures=list(run.failures),
                    trace_fingerprint=run.trace_fingerprint(),
                )
            )
            if plant:
                SCHED_COUNTERS._bump("sched.planted_finds")

    if mode in ("dpor", "naive"):
        indep = (
            (lambda a, b: not ops_dependent(a, b))
            if mode == "dpor"
            else (lambda a, b: False)
        )
        stack: list[tuple[list[int], dict[int, PendingOp]]] = [([], {})]
        while stack:
            if out_of_budget():
                res.complete = False
                break
            forced, entry_sleep = stack.pop()
            policy = _ExplorerPolicy(forced, entry_sleep, indep)
            run = _execute(scenario, plant, policy)
            if run.pruned:
                res.prunes += 1
                SCHED_COUNTERS._bump("sched.dpor_prunes")
            else:
                res.prunes += policy.sleep_skips
                SCHED_COUNTERS._bump("sched.dpor_prunes", policy.sleep_skips)
                record(run)
            # LIFO: depth-first over the reduced tree; sibling sleep sets
            # were precomputed at push time so pop order is irrelevant
            stack.extend(reversed(policy.branch_points))
        else:
            res.complete = True
    elif mode in ("random", "pos"):
        rng = random.Random(seed)
        while not out_of_budget():
            policy = (
                _RandomPolicy(random.Random(rng.randrange(2**31)))
                if mode == "random"
                else _POSPolicy(random.Random(rng.randrange(2**31)))
            )
            record(_execute(scenario, plant, policy))
        res.complete = False
    else:
        raise SchedInfraError(f"unknown exploration mode: {mode}")
    res.elapsed_s = time.monotonic() - t0
    return res


def _failure_signature(failures: list[str]) -> frozenset:
    """Failure identity for shrinking: the set of failure kinds (text up to
    the first ':'), so a shrunk schedule counts iff it fails the same way."""
    return frozenset(f.split(":", 1)[0] for f in failures)


def shrink_schedule(scenario: str, choices: list[int], plant: bool = False,
                    max_steps: int = 400) -> tuple[list[int], RunResult]:
    """Choice-prefix ddmin (chaos.fuzz.shrink's skeleton over choice lists):
    remove chunks at halving granularity, then zero surviving choices.
    Tolerant interpretation makes every candidate subsequence executable."""
    base = run_schedule(scenario, list(choices), plant)
    if not base.failures:
        raise SchedInfraError("shrink_schedule: schedule does not fail")
    want = _failure_signature(base.failures)
    budget = [max_steps]

    def violates(cand: list[int]) -> Optional[RunResult]:
        if budget[0] <= 0:
            return None
        budget[0] -= 1
        SCHED_COUNTERS._bump("sched.shrinks")
        run = run_schedule(scenario, cand, plant)
        return run if _failure_signature(run.failures) & want else None

    cur = list(choices)
    best = base
    # pass 1: ddmin chunk removal
    gran = max(1, len(cur) // 2)
    while gran >= 1 and budget[0] > 0:
        i = 0
        reduced = False
        while i < len(cur) and budget[0] > 0:
            cand = cur[:i] + cur[i + gran:]
            run = violates(cand)
            if run is not None:
                cur, best, reduced = cand, run, True
            else:
                i += gran
        if not reduced:
            if gran == 1:
                break
            gran = max(1, gran // 2)
    # pass 2: zero each surviving choice (smaller ids replay first-enabled)
    for i in range(len(cur)):
        if cur[i] == 0 or budget[0] <= 0:
            continue
        cand = cur[:i] + [0] + cur[i + 1:]
        run = violates(cand)
        if run is not None:
            cur, best = cand, run
    return cur, best


# ---------------------------------------------------------------------------
# tier-1 smoke, fuzz coverage feed, CLI
# ---------------------------------------------------------------------------


def tier1_smoke(total_budget_s: Optional[float] = None,
                seed: int = 0) -> dict[str, Any]:
    """The budgeted library sweep tier-1 runs: exhaustive DPOR (with
    certificate) on the two smallest scenarios, POS sampling on the rest.
    Honors OPENR_SCHED_BUDGET_S; sheds loudly, never silently."""
    total = budget_s(20.0) if total_budget_s is None else total_budget_s
    t0 = time.monotonic()
    names = list(SCENARIOS)
    out: dict[str, Any] = {
        "scenarios": {},
        "failures": [],
        "shed": [],
        "budget_s": total,
    }
    for name in names:
        left = total - (time.monotonic() - t0)
        if left <= 0:
            out["shed"].append(name)
            continue
        if name in EXHAUSTIVE_SCENARIOS:
            r = explore(name, seed=seed, mode="dpor",
                        wall_budget_s=min(left, total / 2))
        else:
            r = explore(name, seed=seed, mode="pos", max_schedules=40,
                        wall_budget_s=min(left, total / 6))
        out["scenarios"][name] = {
            "mode": r.mode,
            "schedules": r.schedules,
            "prunes": r.prunes,
            "complete": r.complete,
            "elapsed_s": round(r.elapsed_s, 3),
        }
        for f in r.failures:
            out["failures"].append(
                {"schedule_id": f.schedule_id, "failures": f.failures}
            )
    return out


def sample_tokens(seed: int, n_schedules: int = 8,
                  scenarios: Optional[list[str]] = None) -> set[str]:
    """Cheap random-walk batch for the chaos fuzzer's coverage map: returns
    `sched:<scenario>:<choice-fingerprint>` tokens so timeline search and
    schedule search compose in one frontier."""
    rng = random.Random(seed)
    names = scenarios or list(SCENARIOS)
    tokens: set[str] = set()
    per = max(1, n_schedules // len(names))
    for name in names:
        r = explore(name, seed=rng.randrange(2**31), mode="random",
                    max_schedules=per, wall_budget_s=5.0)
        tokens |= r.coverage_tokens
    return tokens


def run_cli(args: Any) -> int:
    """`--sched` entry for analysis/cli.py: 0 clean, 1 findings, 2 infra."""
    try:
        if getattr(args, "sched_replay", None):
            run = replay_schedule(args.sched_replay)
            print(f"replayed {args.sched_replay}: trace={run.trace_fingerprint()} "
                  f"steps={run.steps}")
            for f in run.failures:
                print(f"  FAIL {f}")
            return 1 if run.failures else 0
        if getattr(args, "sched_shrink", None):
            scenario, plant, seed, choices = parse_schedule_id(args.sched_shrink)
            shrunk, run = shrink_schedule(scenario, choices, plant)
            sid = format_schedule_id(scenario, seed, shrunk, plant)
            print(f"shrunk {len(choices)} -> {len(shrunk)} choices: {sid}")
            for f in run.failures:
                print(f"  FAIL {f}")
            return 1 if run.failures else 0
        summary = tier1_smoke(seed=getattr(args, "sched_seed", 0) or 0)
        for name, row in summary["scenarios"].items():
            cert = "exhaustive" if row["complete"] else "sampled"
            print(
                f"sched {name}: {row['schedules']} schedules "
                f"({row['mode']}, {cert}), {row['prunes']} pruned, "
                f"{row['elapsed_s']}s"
            )
        for name in summary["shed"]:
            print(f"sched {name}: SHED (budget exhausted)")
        for f in summary["failures"]:
            print(f"sched FAIL {f['schedule_id']}: {f['failures']}")
        return 1 if summary["failures"] else 0
    except (SchedInfraError, ValueError) as e:
        # ValueError = malformed/unknown schedule id: the EXPLORER was
        # misused, not "findings" — same contract as AnalysisError
        print(f"sched infra error: {e}")
        return 2
