"""Static invariant checker for openr_tpu.

Three AST checker families — jit hygiene, thread discipline, counter
hygiene — are stdlib-ast only and never import jax.  The program-level
family (``--programs``) is the exception: it imports jax to trace every
jit root and residency-ladder cell to a jaxpr and audit donation, dtype,
callback, constant-size and op-count contracts (analysis/programs.py).
Documented in docs/ARCHITECTURE.md ("Static invariants" and
"Program-level invariants").  Run with ``python -m openr_tpu.analysis
openr_tpu/`` or ``scripts/lint.py``.
"""

from .core import (  # noqa: F401
    ALL_RULES,
    AnalysisConfig,
    AnalysisError,
    Finding,
    Reporter,
    Severity,
    load_config,
    run_analysis,
)
