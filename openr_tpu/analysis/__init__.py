"""Static invariant checker for openr_tpu (stdlib-ast only, no jax import).

Three checker families — jit hygiene, thread discipline, counter hygiene —
documented in docs/ARCHITECTURE.md ("Static invariants").  Run with
``python -m openr_tpu.analysis openr_tpu/`` or ``scripts/lint.py``.
"""

from .core import (  # noqa: F401
    ALL_RULES,
    AnalysisConfig,
    Finding,
    Reporter,
    Severity,
    load_config,
    run_analysis,
)
