"""Core machinery for the openr-tpu static analyzer.

Pure stdlib (``ast`` + ``tokenize``): the analyzer must stay importable in
environments without jax so that ``python -m openr_tpu.analysis`` can run as
a pre-test lint step anywhere, including CI boxes with no accelerator stack.

The pieces here are shared by all three checker families (jit hygiene,
thread discipline, counter hygiene):

- :class:`Finding` / :class:`Severity` — one diagnostic, pointing at a
  rule id, file, line and column.
- suppression parsing — ``# openr: disable=<rule>[,<rule>...]`` on the
  flagged line (or on a comment line directly above it, for long lines)
  silences matching findings.  ``# openr: disable=all`` silences every rule
  on that line.
- :class:`AnalysisConfig` — loaded from ``[tool.openr-analysis]`` in
  pyproject.toml.  Python 3.10 has no ``tomllib``, so a minimal parser for
  the small subset we use (strings, booleans, arrays of strings) backs the
  stdlib import when it is unavailable.
- :class:`SourceFile` / :func:`walk_python_files` — parsed-file cache and
  target discovery.
"""

from __future__ import annotations

import ast
import enum
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Iterator, Sequence


class Severity(enum.Enum):
    ERROR = "error"
    WARNING = "warning"

    def __str__(self) -> str:  # pragma: no cover - trivial
        return self.value


class AnalysisError(Exception):
    """The analyzer itself could not run (bad config, git failure, trace
    failure in the program auditor) — distinct from findings, so the CLI
    can exit 2 ("broken analyzer") instead of 1 ("dirty tree")."""


@dataclass(frozen=True)
class Finding:
    """One diagnostic raised by a rule against a source location."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    severity: Severity = Severity.ERROR

    def format(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.severity.value}[{self.rule}] {self.message}"
        )


# ---------------------------------------------------------------------------
# Suppressions
# ---------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(r"#\s*openr:\s*disable=([A-Za-z0-9_\-,\s]+)")


@dataclass
class SuppressionDecl:
    """One ``# openr: disable=`` comment: the declaration line, the rules
    it names, the code lines it covers, and which rules actually matched a
    finding (feeds the suppression-unused rule)."""

    line: int
    rules: frozenset[str]
    covered: set[int] = field(default_factory=set)
    used_rules: set[str] = field(default_factory=set)


@dataclass
class Suppressions:
    """Per-file map of line -> set of suppressed rule ids ('all' wildcard)."""

    by_line: dict[int, set[str]] = field(default_factory=dict)
    #: one record per disable comment, for unused-suppression reporting
    decls: list[SuppressionDecl] = field(default_factory=list)
    #: suppressions that matched a finding, keyed (covered line, rule)
    used: set[tuple[int, str]] = field(default_factory=set)

    def matches(self, line: int, rule: str) -> bool:
        rules = self.by_line.get(line)
        if not rules or (rule not in rules and "all" not in rules):
            return False
        self.used.add((line, rule))
        for decl in self.decls:
            if line in decl.covered and (
                rule in decl.rules or "all" in decl.rules
            ):
                decl.used_rules.add(rule)
        return True


def collect_suppressions(source: str) -> Suppressions:
    """Scan comments for ``# openr: disable=`` markers.

    A marker on a *standalone* comment line applies to the next non-comment
    line as well, so long statements can carry their suppression above them.
    """
    sup = Suppressions()
    pending: set[str] | None = None
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return sup
    # Lines that contain any non-comment code, to tell standalone comment
    # lines apart from trailing comments.
    code_lines: set[int] = set()
    for tok in tokens:
        if tok.type in (
            tokenize.COMMENT,
            tokenize.NL,
            tokenize.NEWLINE,
            tokenize.INDENT,
            tokenize.DEDENT,
            tokenize.ENCODING,
            tokenize.ENDMARKER,
        ):
            continue
        for ln in range(tok.start[0], tok.end[0] + 1):
            code_lines.add(ln)
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        line = tok.start[0]
        covered = {line}
        sup.by_line.setdefault(line, set()).update(rules)
        if line not in code_lines:
            # Standalone comment: also cover the next code line.
            nxt = min((ln for ln in code_lines if ln > line), default=None)
            if nxt is not None:
                sup.by_line.setdefault(nxt, set()).update(rules)
                covered.add(nxt)
        sup.decls.append(
            SuppressionDecl(line=line, rules=frozenset(rules), covered=covered)
        )
    return sup


# ---------------------------------------------------------------------------
# Configuration
# ---------------------------------------------------------------------------

ALL_RULES: dict[str, str] = {
    # jit hygiene (openr_tpu/analysis/jit.py)
    "jit-host-sync": "host-sync construct inside a traced (jitted) context",
    "jit-tracer-branch": "Python control flow on a tracer-derived value",
    "jit-static-hygiene": "static-arg misuse that breaks caching or tracing",
    "jit-dispatch-sync": "implicit device->host sync in jit dispatch code",
    "jit-unbucketed-dispatch": (
        "daemon code calls a jitted kernel directly, bypassing the device "
        "engine front-end (no shape bucketing, residency or accounting)"
    ),
    # thread discipline (openr_tpu/analysis/threads.py)
    "thread-cross-module-write": (
        "attribute write into another module, bypassing queue/ctrl seams"
    ),
    "thread-queue-registration": (
        "ReplicateQueue created in the daemon but absent from the named-queue dict"
    ),
    "lock-order": (
        "lock acquisition order inconsistent across the whole-tree lock "
        "graph (cycle: a deadlock is one unlucky schedule away)"
    ),
    "guarded-by": (
        "attribute written under a lock at one site and bare at another "
        "(the lock protects nothing if any writer skips it)"
    ),
    "thread-shutdown-order": (
        "consumer module stopped before its registered queue is closed "
        "(stop() can wedge on a get() nobody will ever wake)"
    ),
    "blocking-call-in-eventbase": (
        "unbounded blocking call (time.sleep / Future.result / Queue.get "
        "without timeout) reachable from code running on a module's "
        "event-base loop — one such call parks every fiber on the module"
    ),
    # counter hygiene (openr_tpu/analysis/counters.py)
    "counter-name": "counter literal violates the module.name convention",
    "counter-registry": (
        "counter bumped but unreachable from OpenrCtrlHandler._all_counters"
    ),
    "counter-duplicate": "one counter bumped under two spellings",
    "counter-unbumped": (
        "counter pre-seeded in a registry literal but never bumped anywhere"
    ),
    # lint of the lint (openr_tpu/analysis/core.py)
    "suppression-unused": (
        "'# openr: disable=' marker whose rule never fires on that line"
    ),
    # program-level invariants (openr_tpu/analysis/programs.py; these trace
    # real jaxprs, so they only run under --programs / run_analysis(programs=True))
    "program-donation": (
        "donate_argnums declared but XLA does not alias the buffer "
        "(donation silently dropped: aval mismatch between input and outputs)"
    ),
    "program-dtype": (
        "float64 or weak-type float promotion inside a traced program"
    ),
    "program-callback": (
        "host callback / debug primitive inside a compiled program"
    ),
    "program-constants": (
        "large closed-over constant embedded in a compiled program "
        "(re-uploaded on every compile)"
    ),
    "program-budget": (
        "jaxpr primitive count exceeds the checked-in op-count budget"
    ),
    "program-coverage": (
        "jit root discovered by the AST pass but never traced by the "
        "program auditor's drivers"
    ),
}

#: rules that require tracing real programs (jax import); they are executed
#: only when run_analysis(..., programs=True) / the CLI --programs flag
PROGRAM_RULES = frozenset(
    r for r in ALL_RULES if r.startswith("program-")
)


@dataclass
class AnalysisConfig:
    """Knobs read from ``[tool.openr-analysis]`` in pyproject.toml."""

    #: rule ids to run; defaults to every known rule
    enable: list[str] = field(default_factory=lambda: sorted(ALL_RULES))
    #: rule ids to drop from `enable`
    disable: list[str] = field(default_factory=list)
    #: path prefixes (relative to the package root's parent) skipped entirely
    exclude: list[str] = field(default_factory=list)
    #: files/dirs whose call graphs the jit checkers analyze
    jit_paths: list[str] = field(default_factory=list)
    #: files/dirs allowed to dispatch jitted kernels directly (the sanctioned
    #: device-engine front-end); everything else outside jit_paths is daemon
    #: code and must route dispatch through the engine
    engine_dispatch_paths: list[str] = field(
        default_factory=lambda: ["openr_tpu/device"]
    )
    #: extra top-level counter prefixes treated as exported (beyond the ones
    #: discovered by parsing OpenrCtrlHandler._all_counters)
    counter_extra_prefixes: list[str] = field(default_factory=list)
    #: attribute names treated as module handles by the thread checker
    module_attrs: list[str] = field(default_factory=list)
    #: program-constants threshold: closed-over consts above this many bytes
    #: are flagged (they re-upload per compile instead of living in residency)
    program_const_max_bytes: int = 4096
    #: jit roots (bare function names) allowed to carry float dtypes in their
    #: jaxpr (e.g. differentiable/loss kernels); everything else is integer
    #: min-plus arithmetic and any float is a promotion bug
    program_float_allowed: list[str] = field(default_factory=list)
    #: dotted class paths the OPENR_TSAN dynamic race detector instruments
    #: (openr_tpu/analysis/race.py); empty means its built-in defaults
    tsan_tracked_paths: list[str] = field(default_factory=list)
    #: `Class.attr` lock-graph nodes excluded from the lock-order rule
    lock_order_exclude: list[str] = field(default_factory=list)

    def active_rules(self) -> set[str]:
        return {r for r in self.enable if r in ALL_RULES} - set(self.disable)

    def is_excluded(self, path: Path, root: Path) -> bool:
        try:
            rel = path.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = path.as_posix()
        return any(
            rel == ex.rstrip("/") or rel.startswith(ex.rstrip("/") + "/")
            for ex in self.exclude
        )


def _parse_toml_minimal(text: str) -> dict[str, dict[str, object]]:
    """Parse the tiny TOML subset the analyzer config uses.

    Handles ``[section.headers]``, ``key = "string" | true | false`` and
    (possibly multiline) arrays of strings.  Python 3.10 ships no tomllib;
    this keeps the analyzer dependency-free there.
    """
    out: dict[str, dict[str, object]] = {}
    section: dict[str, object] | None = None
    lines = text.splitlines()
    i = 0
    while i < len(lines):
        raw = lines[i].strip()
        i += 1
        if not raw or raw.startswith("#"):
            continue
        if raw.startswith("[") and raw.endswith("]"):
            name = raw[1:-1].strip().strip('"')
            section = out.setdefault(name, {})
            continue
        if section is None or "=" not in raw:
            continue
        key, _, val = raw.partition("=")
        key = key.strip().strip('"')
        val = val.strip()
        if val.startswith("["):
            # Accumulate until the closing bracket (arrays may span lines).
            buf = val
            while "]" not in buf and i < len(lines):
                buf += " " + lines[i].strip()
                i += 1
            items = re.findall(r'"((?:[^"\\]|\\.)*)"|\'([^\']*)\'', buf)
            section[key] = [a if a else b for a, b in items]
        elif val in ("true", "false"):
            section[key] = val == "true"
        elif re.fullmatch(r"-?\d+", val.split("#")[0].strip()):
            section[key] = int(val.split("#")[0].strip())
        else:
            m = re.match(r'"((?:[^"\\]|\\.)*)"|\'([^\']*)\'', val)
            if m:
                section[key] = m.group(1) if m.group(1) is not None else m.group(2)
    return out


def load_config(start: Path) -> tuple[AnalysisConfig, Path]:
    """Find pyproject.toml at or above `start`; return (config, project root).

    Falls back to defaults (and `start` as root) when no pyproject is found.
    """
    cur = start.resolve()
    if cur.is_file():
        cur = cur.parent
    for candidate in [cur, *cur.parents]:
        py = candidate / "pyproject.toml"
        if py.is_file():
            text = py.read_text(encoding="utf-8")
            try:
                import tomllib  # Python 3.11+

                data = tomllib.loads(text)
            except ModuleNotFoundError:
                data = _parse_toml_minimal(text)
            tool = data.get("tool", {})
            if isinstance(tool, dict) and "openr-analysis" in tool:
                raw = tool["openr-analysis"]
            else:
                raw = data.get("tool.openr-analysis", {})
            cfg = AnalysisConfig()
            if isinstance(raw, dict):
                for key in (
                    "enable",
                    "disable",
                    "exclude",
                    "jit_paths",
                    "engine_dispatch_paths",
                    "counter_extra_prefixes",
                    "module_attrs",
                    "program_float_allowed",
                    "tsan_tracked_paths",
                    "lock_order_exclude",
                ):
                    val = raw.get(key)
                    if isinstance(val, list):
                        setattr(cfg, key, [str(v) for v in val])
                val = raw.get("program_const_max_bytes")
                if isinstance(val, int) and not isinstance(val, bool):
                    cfg.program_const_max_bytes = val
            return cfg, candidate
    return AnalysisConfig(), cur


# ---------------------------------------------------------------------------
# Source files
# ---------------------------------------------------------------------------


@dataclass
class SourceFile:
    """A parsed source file plus its suppression map."""

    path: Path
    rel: str
    source: str
    tree: ast.Module
    suppressions: Suppressions

    @classmethod
    def parse(cls, path: Path, root: Path) -> "SourceFile | None":
        try:
            source = path.read_text(encoding="utf-8")
            tree = ast.parse(source, filename=str(path))
        except (OSError, SyntaxError):
            return None
        try:
            rel = path.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = path.as_posix()
        return cls(
            path=path,
            rel=rel,
            source=source,
            tree=tree,
            suppressions=collect_suppressions(source),
        )


def walk_python_files(targets: Sequence[Path]) -> Iterator[Path]:
    seen: set[Path] = set()
    for target in targets:
        if target.is_file() and target.suffix == ".py":
            p = target.resolve()
            if p not in seen:
                seen.add(p)
                yield target
        elif target.is_dir():
            for dirpath, dirnames, filenames in os.walk(target):
                dirnames[:] = [
                    d for d in dirnames if d != "__pycache__" and not d.startswith(".")
                ]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        p = (Path(dirpath) / fn).resolve()
                        if p not in seen:
                            seen.add(p)
                            yield Path(dirpath) / fn


class Reporter:
    """Collects findings, applying per-line suppressions."""

    def __init__(self, config: AnalysisConfig) -> None:
        self.config = config
        self.findings: list[Finding] = []
        self.suppressed: list[Finding] = []
        self._active = config.active_rules()
        self._seen: set[tuple[str, str, int, int, str]] = set()

    def emit(
        self,
        sf: SourceFile,
        rule: str,
        node: ast.AST | tuple[int, int],
        message: str,
        severity: Severity = Severity.ERROR,
    ) -> None:
        if rule not in self._active:
            return
        if isinstance(node, tuple):
            line, col = node
        else:
            line = getattr(node, "lineno", 1)
            col = getattr(node, "col_offset", 0)
        key = (rule, sf.rel, line, col, message)
        if key in self._seen:
            return
        self._seen.add(key)
        f = Finding(rule, sf.rel, line, col, message, severity)
        if sf.suppressions.matches(line, rule):
            self.suppressed.append(f)
        else:
            self.findings.append(f)

    def sorted_findings(self) -> list[Finding]:
        return sorted(self.findings, key=lambda f: (f.path, f.line, f.col, f.rule))


def run_analysis(
    targets: Sequence[Path],
    config: AnalysisConfig | None = None,
    root: Path | None = None,
    *,
    programs: bool = False,
    write_budgets: bool = False,
) -> Reporter:
    """Run every enabled checker family over `targets`; return the Reporter.

    ``programs=True`` additionally runs the program-level auditor
    (openr_tpu/analysis/programs.py) — the only family that imports jax and
    traces real jaxprs.  It always audits the whole tree (the jit root set
    from ``jit_paths`` plus the residency-engine ladder), regardless of
    `targets`.  ``write_budgets=True`` regenerates the op-count budget file
    instead of reporting program-budget findings.
    """
    if config is None or root is None:
        cfg, found_root = load_config(targets[0] if targets else Path.cwd())
        config = config or cfg
        root = root or found_root

    files: list[SourceFile] = []
    for path in walk_python_files(targets):
        if config.is_excluded(path, root):
            continue
        sf = SourceFile.parse(path, root)
        if sf is not None:
            files.append(sf)

    reporter = Reporter(config)
    active = config.active_rules()
    # Rules whose checker actually executed this run: a suppression for a
    # rule that never ran (e.g. program-* in an AST-only pass) must not be
    # reported unused.
    executed: set[str] = set()

    jit_rules = {
        "jit-host-sync",
        "jit-tracer-branch",
        "jit-static-hygiene",
        "jit-dispatch-sync",
        "jit-unbucketed-dispatch",
    }
    if active & jit_rules:
        from . import jit

        jit.check(files, reporter, config, root)
        executed |= active & jit_rules
    thread_rules = {
        "thread-cross-module-write",
        "thread-queue-registration",
        "lock-order",
        "guarded-by",
        "thread-shutdown-order",
        "blocking-call-in-eventbase",
    }
    if active & thread_rules:
        from . import threads

        threads.check(files, reporter, config, root)
        executed |= active & thread_rules
    counter_rules = {
        "counter-name",
        "counter-registry",
        "counter-duplicate",
        "counter-unbumped",
    }
    if active & counter_rules:
        from . import counters

        counters.check(files, reporter, config, root)
        executed |= active & counter_rules
    if programs and active & PROGRAM_RULES:
        from . import programs as programs_mod

        programs_mod.check(
            files, reporter, config, root, write_budgets=write_budgets
        )
        executed |= active & PROGRAM_RULES

    if "suppression-unused" in active:
        executed.add("suppression-unused")
        _check_unused_suppressions(files, reporter, executed)
    return reporter


def _check_unused_suppressions(
    files: list[SourceFile], reporter: Reporter, executed: set[str]
) -> None:
    """Lint of the lint: report disable markers whose rule was checked on
    this run but never fired on the covered line(s)."""
    for sf in files:
        for decl in sf.suppressions.decls:
            if "all" in decl.rules:
                # wildcard: unused only when nothing at all matched it
                if not decl.used_rules:
                    reporter.emit(
                        sf,
                        "suppression-unused",
                        (decl.line, 0),
                        "'# openr: disable=all' suppresses nothing here; "
                        "remove it (or name the intended rule)",
                    )
                continue
            dead = sorted(
                r
                for r in decl.rules
                if r in executed and r not in decl.used_rules
            )
            for rule in dead:
                reporter.emit(
                    sf,
                    "suppression-unused",
                    (decl.line, 0),
                    f"suppression for '{rule}' is unused: the rule does not "
                    "fire on this line; remove the marker (stale "
                    "suppressions hide future regressions)",
                )
