"""Dynamic happens-before race detector (``OPENR_TSAN=1``).

The static rules in :mod:`openr_tpu.analysis.threads` prove the *shape* of
the module discipline; this module certifies the *executions*: a
TSan-style vector-clock detector over the daemon's real synchronization
vocabulary.  While armed it builds the happens-before (HB) relation from:

==========================================  ================================
edge                                        established by
==========================================  ================================
lock release -> later acquire               ``threading.Lock``/``RLock``
                                            proxies (Condition/Event ride
                                            their internal locks)
thread fork / join                          ``Thread.start`` (parent clock
                                            snapshot) / ``Thread.join``
queue put -> matching get                   per-item tokens in
                                            ``RWQueue.push``/``get``/
                                            ``try_get``/``aget``
future resolve -> observe                   ``concurrent.futures.Future.
                                            set_result/set_exception`` ->
                                            ``result/exception``
executor submit -> task run                 ``ThreadPoolExecutor.submit``
                                            handoff token
cross-thread marshalling                    ``run_in_event_base_thread``,
                                            ``add_fiber_task``,
                                            ``schedule_timeout``,
                                            ``stop``, ``run_coroutine``
                                            (eventbase handoff wraps)
==========================================  ================================

State on *tracked classes* (``tsan_tracked_paths`` in pyproject's
``[tool.openr-analysis]``; default: ``OpenrEventBase`` and therefore every
module, ``ReplicaRouter``, ``SchedulerReplica``) is recorded through
class-level ``__setattr__``/``__getattribute__`` hooks.  Any write that
races a prior access with no HB path is reported with both thread names,
both stacks, and the attribute — deduped by site pair.

Soundness posture is pure happens-before: no false positives (every
report is a real unordered pair on the schedules observed), but
schedule-dependent false negatives, and over-synchronization through
shared internal locks (a queue's mutex orders *all* critical sections,
not just the matching put/get) hides some true races.  That trade is
deliberate — the armed tier-1 gate must never cry wolf.

Zero cost when off: ``TSAN`` is a module-level constant (``None``) and
every instrumentation seam is a single ``if race.TSAN is not None``
attribute load.  Arm with ``OPENR_TSAN=1`` (read at import; the pytest
``tsan_guard`` fixture and ``python -m openr_tpu.analysis --races`` both
route through :func:`maybe_enable`).  ``OPENR_TSAN_READS=0`` keeps write
tracking but drops read tracking (cheaper; still catches write-write).

This file never imports jax (the analysis-package contract) — tracked
classes are resolved lazily inside :func:`enable`.
"""

from __future__ import annotations

import _thread
import concurrent.futures
import functools
import importlib
import os
import sys
import threading
import weakref
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Optional

#: THE zero-overhead guard.  ``None`` disarms every seam; :func:`enable`
#: swaps in a :class:`RaceDetector`.  Seams must read it late-bound
#: (``race.TSAN``), never ``from ... import TSAN``.
TSAN: Optional["RaceDetector"] = None

_ENV_ARMED = os.environ.get("OPENR_TSAN", "") == "1"

# Real primitives captured before any patching; proxies and the detector
# itself must only ever use these (the detector's own lock being a proxy
# would recurse).
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_RAW_LOCK = _thread.allocate_lock

#: dotted class paths instrumented by default (pyproject
#: ``tsan_tracked_paths`` overrides).  OpenrEventBase covers every module
#: subclass — KvStore, Decision, Fib, QueryScheduler, ... — via the MRO.
DEFAULT_TRACKED_PATHS = [
    "openr_tpu.runtime.eventbase.OpenrEventBase",
    "openr_tpu.serving.router.ReplicaRouter",
    "openr_tpu.serving.router.SchedulerReplica",
]

#: Built-in runtime suppressions: (class name anywhere in the object's
#: MRO, attribute) -> rationale.  Policy (docs/OPERATIONS.md): every
#: entry must say WHY the unordered access is benign; the armed gate
#: treats anything not listed here (or added via
#: ``RaceDetector.suppress``) as a failure.
DEFAULT_RUNTIME_SUPPRESSIONS: dict[tuple[str, str], str] = {
    ("OpenrEventBase", "_timestamp"): (
        "heartbeat gauge: one monotonic float written by the module loop "
        "every 100ms and sampled by the Watchdog thread; readers tolerate "
        "arbitrary staleness (stall threshold 300s >> one beat) and a "
        "torn read of one machine word is impossible under the GIL"
    ),
    ("QueryScheduler", "_accepting"): (
        "monotonic shutdown latch: flips True->False exactly once in "
        "stop()/stopping(); submit() reading it early/late only changes "
        "WHICH loud shed path fires (flag vs closed admission queue) — "
        "a query is never silently accepted after close"
    ),
    ("ReplicaRouter", "_stopped"): (
        "monotonic shutdown latch: set once in stop(); submit()/"
        "_hedge_loop reading stale False costs one extra dispatch whose "
        "reply path re-checks under _lock — never a lost or double "
        "resolution"
    ),
    ("OpenrEventBase", "_thread"): (
        "lifecycle reference: written by run() before start() and read "
        "by in_event_base_thread() from any thread; during a chaos "
        "respawn a peer's in-process call can read it mid-transition, "
        "but the value is one reference word under the GIL and a stale "
        "read only marshals the call instead of inlining it — the "
        "subsequent loop submit either lands or raises into the "
        "caller's sync-failure recovery (kvstore _flood_to_peer)"
    ),
    ("OpenrEventBase", "_loop"): (
        "lifecycle reference: written once by _thread_main at loop "
        "birth; cross-thread users (stop, add_fiber_task, "
        "run_in_event_base_thread) read one reference word under the "
        "GIL.  A stale/None read during a chaos respawn hits the "
        "guarded paths — stop() returns for never-started, "
        "call_soon_threadsafe on a closed loop raises RuntimeError "
        "into callers that treat it as a peer sync failure and "
        "full-sync on reconnect"
    ),
    ("OpenrEventBase", "_started"): (
        "lifecycle Event reference: assigned in __init__ and only read "
        "afterwards (is_running / wait_until_running).  Cross-thread "
        "readers reach a fresh module through the chaos fabric's "
        "addr->store dict; CPython dict publication makes the fully "
        "constructed object visible under the GIL, the detector just "
        "does not model container-mediated handoff (by design)"
    ),
    ("OpenrEventBase", "_stopped"): (
        "lifecycle Event reference: same dict-published pattern as "
        "_started — assigned once in __init__, read via is_running/"
        "wait_until_stopped; the Event object itself synchronizes "
        "internally"
    ),
    ("Decision", "_pending_events"): (
        "deliberately lock-free defer hint (pending_event_hint): the "
        "serving coalesce loop samples an int gauge the decision thread "
        "maintains; the defer wait is bounded by _DEFER_MAX_S whatever "
        "value is read, so staleness only shifts a bounded hold, and a "
        "torn read of one int is impossible under the GIL"
    ),
}

_MAX_FRAMES = 8
_SELF_FILE = os.path.abspath(__file__)


def _capture_stack() -> tuple:
    """Cheap stack sample: up to _MAX_FRAMES (file, line, func) frames,
    skipping this module's own hook frames.  sys._getframe is ~100x
    cheaper than traceback.extract_stack and races never need more than
    the top few user frames to localize."""
    try:
        f = sys._getframe(1)
    except ValueError:  # pragma: no cover
        return ()
    out = []
    while f is not None and len(out) < _MAX_FRAMES:
        code = f.f_code
        if os.path.abspath(code.co_filename) != _SELF_FILE:
            out.append((code.co_filename, f.f_lineno, code.co_name))
        f = f.f_back
    return tuple(out)


def _leq(a: dict, b: dict) -> bool:
    """Vector-clock partial order: a <= b."""
    get = b.get
    for t, v in a.items():
        if v > get(t, 0):
            return False
    return True


# Thread ids are GLOBAL (not per-detector): _ThreadState objects live on
# Thread objects and survive enable/disable cycles, so a fresh detector
# reusing tid numbers would collide with stale states.
_NEXT_TID = [0]
_TID_LOCK = _RAW_LOCK()

# Per-OS-thread detector state: .depth (reentrancy guard, set BEFORE any
# work that could recurse into a proxy lock) and .state (_ThreadState
# cache).  A C-level threading.local — attribute access takes no lock and
# cannot recurse.  TLS dies with its OS thread, so ident reuse can never
# resurrect a dead thread's clock through this cache.
_TLS = threading.local()


class _ThreadState:
    """Per-thread vector clock.  The clock dict is mutated only by its
    owning thread; published snapshots are copies and immutable by
    convention."""

    __slots__ = ("tid", "name", "clock", "_snap")

    def __init__(self, tid: int, name: str) -> None:
        self.tid = tid
        self.name = name
        self.clock: dict[int, int] = {tid: 1}
        self._snap: Optional[dict[int, int]] = None

    def snapshot(self) -> dict[int, int]:
        s = self._snap
        if s is None:
            s = self._snap = dict(self.clock)
        return s

    def bump(self) -> None:
        self.clock[self.tid] = self.clock.get(self.tid, 0) + 1
        self._snap = None

    def join(self, other: dict[int, int]) -> None:
        c = self.clock
        for t, v in other.items():
            if c.get(t, 0) < v:
                c[t] = v
                self._snap = None


class _Access:
    """One recorded access: who, at what clock, from where."""

    __slots__ = ("tid", "clock", "thread_name", "stack")

    def __init__(self, tid: int, clock: dict, thread_name: str, stack: tuple):
        self.tid = tid
        self.clock = clock  # immutable snapshot
        self.thread_name = thread_name
        self.stack = stack

    @property
    def site(self) -> tuple:
        return self.stack[0][:2] if self.stack else ("<unknown>", 0)


class _VarState:
    """Race metadata for one (object, attribute): the last write plus the
    latest read per thread (per-thread clocks are monotone, so the latest
    read dominates earlier ones for race purposes)."""

    __slots__ = ("last_write", "reads")

    def __init__(self) -> None:
        self.last_write: Optional[_Access] = None
        self.reads: dict[int, _Access] = {}


def _fmt_stack(stack: tuple, indent: str = "      ") -> str:
    if not stack:
        return indent + "<no frames>"
    return "\n".join(
        f"{indent}{fn}:{line} in {func}" for (fn, line, func) in stack
    )


@dataclass(frozen=True)
class RaceFinding:
    """One unordered access pair on tracked state."""

    kind: str  # "write-write" | "read-write" | "write-read"
    cls_name: str
    attr: str
    prior_thread: str
    prior_stack: tuple
    thread: str
    stack: tuple

    def format(self) -> str:
        prior_kind, cur_kind = {
            "write-write": ("write", "write"),
            "read-write": ("read", "write"),
            "write-read": ("write", "read"),
        }[self.kind]
        return (
            f"{self.kind} race on {self.cls_name}.{self.attr}\n"
            f"  {cur_kind} by thread {self.thread!r} at:\n"
            f"{_fmt_stack(self.stack)}\n"
            f"  unordered against prior {prior_kind} by thread "
            f"{self.prior_thread!r} at:\n"
            f"{_fmt_stack(self.prior_stack)}"
        )


def format_findings(findings: Iterable[RaceFinding]) -> str:
    items = list(findings)
    body = "\n\n".join(f.format() for f in items)
    return (
        f"OPENR_TSAN: {len(items)} unsuppressed race finding"
        f"{'s' if len(items) != 1 else ''}\n\n{body}"
    )


class RaceDetector:
    """Vector-clock happens-before engine.

    All shared structures are guarded by a RAW ``_thread`` lock (never a
    proxy — the detector must not instrument itself).  Per-thread clocks
    are lock-free: mutated only by their owner; cross-thread visibility
    rides immutable snapshots."""

    def __init__(
        self, suppressions: Optional[dict[tuple[str, str], str]] = None
    ) -> None:
        self._lock = _RAW_LOCK()
        self._vars: dict[tuple[int, str], _VarState] = {}
        self._by_obj: dict[int, set[str]] = {}
        self._live: dict[int, Any] = {}
        # weakref callbacks may fire mid-GC while OUR lock is held, so
        # they only append (GIL-atomic) here; drained under the lock
        self._dead: list[int] = []
        self._seen: set = set()
        self.findings: list[RaceFinding] = []
        self.suppressed: list[tuple[RaceFinding, str]] = []
        self.suppressions = dict(DEFAULT_RUNTIME_SUPPRESSIONS)
        if suppressions:
            self.suppressions.update(suppressions)
        self._mro_names: dict[type, tuple[str, ...]] = {}
        self.track_reads = os.environ.get("OPENR_TSAN_READS", "1") != "0"

    # -- thread state --------------------------------------------------------

    @staticmethod
    def _make_state(tls: Any) -> Optional[_ThreadState]:
        """First hook on this OS thread: allocate a vector clock and join
        the fork token Thread.start stashed.  NEVER calls
        threading.current_thread() — during thread bootstrap (before
        _active registration) it would manufacture a _DummyThread whose
        __init__ re-enters our lock proxies, recursing forever.  An
        unregistered thread is simply not instrumented yet: only
        Thread-internal bootstrap locks run in that window."""
        t = threading._active.get(_thread.get_ident())
        if t is None:
            return None
        with _TID_LOCK:
            _NEXT_TID[0] += 1
            tid = _NEXT_TID[0]
        st = _ThreadState(tid, t.name)
        parent = t.__dict__.get("_tsan_parent")
        if parent is not None:
            st.join(parent)
        tls.state = st
        # also visible to joiners (the Thread.join patch reads it)
        t._tsan_state = st
        return st

    def _enter(self) -> Optional[_ThreadState]:
        tls = _TLS
        if getattr(tls, "depth", 0):
            return None
        tls.depth = 1  # before ANY work: arms the recursion guard
        st = getattr(tls, "state", None)
        if st is None:
            try:
                st = self._make_state(tls)
            except BaseException:  # pragma: no cover
                tls.depth = 0
                raise
            if st is None:
                tls.depth = 0
                return None
        return st

    @staticmethod
    def _exit(st: _ThreadState) -> None:
        _TLS.depth = 0

    # -- HB edge primitives --------------------------------------------------

    def publish_token(self) -> Optional[dict]:
        """Snapshot the calling thread's clock (an HB source) and advance
        past it; pair with :meth:`acquire_token` on the receiving side."""
        st = self._enter()
        if st is None:
            return None
        try:
            snap = st.snapshot()
            st.bump()
            return snap
        finally:
            self._exit(st)

    def acquire_token(self, token: Optional[dict]) -> None:
        if token is None:
            return
        st = self._enter()
        if st is None:
            return
        try:
            st.join(token)
        finally:
            self._exit(st)

    # fork/join spellings for readability at the Thread patch sites
    fork_token = publish_token

    def wrap_handoff(self, fn: Callable) -> Callable:
        """Publish now; the returned callable joins before running `fn`.
        The edge for every cross-thread closure handoff
        (call_soon_threadsafe, executor submit)."""
        token = self.publish_token()

        @functools.wraps(fn)
        def _handoff(*args: Any, **kwargs: Any) -> Any:
            self.acquire_token(token)
            return fn(*args, **kwargs)

        return _handoff

    def wrap_coro(self, coro):
        """Handoff edge for a coroutine about to be scheduled on another
        loop (run_coroutine_threadsafe)."""
        token = self.publish_token()

        async def _joined():
            self.acquire_token(token)
            return await coro

        return _joined()

    def on_acquire(self, lock: Any) -> None:
        c = lock._tsan_clock
        if c is None:
            return
        st = self._enter()
        if st is None:
            return
        try:
            st.join(c)
        finally:
            self._exit(st)

    def on_release(self, lock: Any) -> None:
        st = self._enter()
        if st is None:
            return
        try:
            lock._tsan_clock = st.snapshot()
            st.bump()
        finally:
            self._exit(st)

    # -- access recording ----------------------------------------------------

    def record_read(self, obj: Any, name: str) -> None:
        """__getattribute__ hook body: record only instance-dict reads
        (skips methods, class attrs, descriptors)."""
        if name.startswith(("_tsan", "__")):
            return
        try:
            d = object.__getattribute__(obj, "__dict__")
        except AttributeError:
            return
        if name in d:
            self.record_access(obj, name, False)

    def record_access(self, obj: Any, attr: str, is_write: bool) -> None:
        if attr.startswith("_tsan"):
            return
        st = self._enter()
        if st is None:
            return
        try:
            snap = st.snapshot()
            clock = st.clock
            tp = type(obj)
            mro = self._mro_names.get(tp)
            if mro is None:
                # benign lost-update under the GIL: idempotent value
                mro = self._mro_names[tp] = tuple(
                    c.__name__ for c in tp.__mro__
                )
            oid = id(obj)
            with self._lock:
                if self._dead:
                    self._drain_dead()
                key = (oid, attr)
                var = self._vars.get(key)
                if var is None:
                    var = self._vars[key] = _VarState()
                    self._by_obj.setdefault(oid, set()).add(attr)
                    self._watch(obj, oid)
                if not is_write:
                    prev = var.reads.get(st.tid)
                    if prev is not None and prev.clock is snap:
                        return  # same epoch: already checked + recorded
                    acc = _Access(st.tid, snap, st.name, _capture_stack())
                    lw = var.last_write
                    if (
                        lw is not None
                        and lw.tid != st.tid
                        and not _leq(lw.clock, clock)
                    ):
                        self._report("write-read", mro, attr, lw, acc)
                    var.reads[st.tid] = acc
                    return
                acc = _Access(st.tid, snap, st.name, _capture_stack())
                lw = var.last_write
                if (
                    lw is not None
                    and lw.tid != st.tid
                    and not _leq(lw.clock, clock)
                ):
                    self._report("write-write", mro, attr, lw, acc)
                for rd in var.reads.values():
                    if rd.tid != st.tid and not _leq(rd.clock, clock):
                        self._report("read-write", mro, attr, rd, acc)
                var.reads.clear()
                var.last_write = acc
        finally:
            self._exit(st)

    def _watch(self, obj: Any, oid: int) -> None:
        # under self._lock; drop var state when the object dies so a
        # recycled id() can never pair a new object against stale accesses
        if oid in self._live:
            return
        dead = self._dead
        try:
            self._live[oid] = weakref.ref(
                obj, lambda _r, oid=oid, dead=dead: dead.append(oid)
            )
        except TypeError:
            self._live[oid] = None

    def _drain_dead(self) -> None:
        # under self._lock; callbacks may append concurrently (no lock),
        # so pop one-at-a-time instead of swapping the list out
        d = self._dead
        while d:
            try:
                oid = d.pop()
            except IndexError:  # pragma: no cover
                break
            self._live.pop(oid, None)
            for attr in self._by_obj.pop(oid, ()):
                self._vars.pop((oid, attr), None)

    # -- reporting -----------------------------------------------------------

    def _report(
        self,
        kind: str,
        mro: tuple[str, ...],
        attr: str,
        prior: _Access,
        cur: _Access,
    ) -> None:
        # deduped by site pair: the same two code locations racing on the
        # same attribute report once, however many objects/iterations hit.
        # The pair is unordered (which access is "prior" depends on the
        # schedule), so the key must not depend on processing order —
        # annotate each site with its access kind and take the frozenset
        prior_kind, cur_kind = {
            "write-write": ("w", "w"),
            "read-write": ("r", "w"),
            "write-read": ("w", "r"),
        }[kind]
        key = (
            mro[0],
            attr,
            frozenset(((prior_kind, prior.site), (cur_kind, cur.site))),
        )
        if key in self._seen:
            return
        self._seen.add(key)
        f = RaceFinding(
            kind=kind,
            cls_name=mro[0],
            attr=attr,
            prior_thread=prior.thread_name,
            prior_stack=prior.stack,
            thread=cur.thread_name,
            stack=cur.stack,
        )
        for name in mro:
            why = self.suppressions.get((name, attr))
            if why is not None:
                self.suppressed.append((f, why))
                return
        self.findings.append(f)

    def suppress(self, cls_name: str, attr: str, rationale: str) -> None:
        """Register a runtime suppression.  `rationale` is mandatory —
        the suppression policy (docs/OPERATIONS.md) requires every entry
        to argue why the unordered pair is benign."""
        if not rationale or not rationale.strip():
            raise ValueError("race suppressions require a written rationale")
        self.suppressions[(cls_name, attr)] = rationale

    def drain(self) -> list[RaceFinding]:
        """Return-and-clear unsuppressed findings (the tsan_guard gate)."""
        with self._lock:
            out, self.findings = self.findings, []
        return out


# ---------------------------------------------------------------------------
# Lock proxies (installed as threading.Lock / threading.RLock while armed)
# ---------------------------------------------------------------------------


class TsanLock:
    """threading.Lock stand-in adding release->acquire HB edges.  Null-safe:
    objects outliving disable() degrade to passthrough."""

    __slots__ = ("_tsan_inner", "_tsan_clock")

    def __init__(self) -> None:
        self._tsan_inner = _REAL_LOCK()
        self._tsan_clock: Optional[dict] = None

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._tsan_inner.acquire(blocking, timeout)
        if ok:
            det = TSAN
            if det is not None:
                det.on_acquire(self)
        return ok

    __enter__ = acquire

    def release(self) -> None:
        det = TSAN
        if det is not None:
            det.on_release(self)
        self._tsan_inner.release()

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def locked(self) -> bool:
        return self._tsan_inner.locked()

    def _at_fork_reinit(self) -> None:  # pragma: no cover
        self._tsan_inner._at_fork_reinit()

    def __repr__(self) -> str:  # pragma: no cover
        return f"<TsanLock {self._tsan_inner!r}>"


class TsanRLock:
    """threading.RLock stand-in: HB edges only on the outermost
    acquire/release; implements the Condition protocol
    (_is_owned/_release_save/_acquire_restore)."""

    __slots__ = ("_tsan_inner", "_tsan_clock", "_tsan_count")

    def __init__(self) -> None:
        self._tsan_inner = _REAL_RLOCK()
        self._tsan_clock: Optional[dict] = None
        # recursion depth; only touched while the inner lock is held
        self._tsan_count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._tsan_inner.acquire(blocking, timeout)
        if ok:
            self._tsan_count += 1
            if self._tsan_count == 1:
                det = TSAN
                if det is not None:
                    det.on_acquire(self)
        return ok

    __enter__ = acquire

    def release(self) -> None:
        if self._tsan_count == 1:
            det = TSAN
            if det is not None:
                det.on_release(self)
        self._tsan_inner.release()  # raises first if not owned
        self._tsan_count -= 1

    def __exit__(self, *exc: Any) -> None:
        self.release()

    # -- Condition protocol --------------------------------------------------

    def _is_owned(self) -> bool:
        return self._tsan_inner._is_owned()

    def _release_save(self):
        det = TSAN
        if det is not None:
            det.on_release(self)
        count, self._tsan_count = self._tsan_count, 0
        return (count, self._tsan_inner._release_save())

    def _acquire_restore(self, saved) -> None:
        count, state = saved
        self._tsan_inner._acquire_restore(state)
        self._tsan_count = count
        det = TSAN
        if det is not None:
            det.on_acquire(self)

    def _at_fork_reinit(self) -> None:  # pragma: no cover
        self._tsan_inner._at_fork_reinit()
        self._tsan_count = 0

    def __repr__(self) -> str:  # pragma: no cover
        return f"<TsanRLock {self._tsan_inner!r}>"


# ---------------------------------------------------------------------------
# Interpreter-level patches (Thread fork/join, Future resolve/observe,
# executor submit)
# ---------------------------------------------------------------------------

_SAVED: dict[str, Any] = {}


def _tsan_thread_start(self: threading.Thread) -> None:
    det = TSAN
    if det is not None:
        # parent-side fork edge; the child joins it lazily on its first
        # detector hook (see RaceDetector._state)
        self._tsan_parent = det.fork_token()
    return _SAVED["thread_start"](self)


def _tsan_thread_join(
    self: threading.Thread, timeout: Optional[float] = None
) -> None:
    r = _SAVED["thread_join"](self, timeout)
    det = TSAN
    if det is not None and not self.is_alive():
        st = self.__dict__.get("_tsan_state")
        if st is not None:
            # the dead child's clock dominates all its accesses
            det.acquire_token(st.clock)
    return r


def _tsan_future_set_result(self, result: Any) -> None:
    det = TSAN
    if det is not None:
        self._tsan_token = det.publish_token()
    return _SAVED["future_set_result"](self, result)


def _tsan_future_set_exception(self, exception: Any) -> None:
    det = TSAN
    if det is not None:
        self._tsan_token = det.publish_token()
    return _SAVED["future_set_exception"](self, exception)


def _tsan_future_result(self, timeout: Optional[float] = None) -> Any:
    try:
        return _SAVED["future_result"](self, timeout)
    finally:
        det = TSAN
        if det is not None:
            tok = getattr(self, "_tsan_token", None)
            if tok is not None:
                det.acquire_token(tok)


def _tsan_future_exception(self, timeout: Optional[float] = None) -> Any:
    try:
        return _SAVED["future_exception"](self, timeout)
    finally:
        det = TSAN
        if det is not None:
            tok = getattr(self, "_tsan_token", None)
            if tok is not None:
                det.acquire_token(tok)


def _tsan_executor_submit(self, fn, /, *args: Any, **kwargs: Any):
    det = TSAN
    if det is not None:
        fn = det.wrap_handoff(fn)
    return _SAVED["executor_submit"](self, fn, *args, **kwargs)


def _install_patches() -> None:
    _SAVED["lock"] = threading.Lock
    _SAVED["rlock"] = threading.RLock
    threading.Lock = TsanLock
    threading.RLock = TsanRLock
    _SAVED["thread_start"] = threading.Thread.start
    _SAVED["thread_join"] = threading.Thread.join
    threading.Thread.start = _tsan_thread_start
    threading.Thread.join = _tsan_thread_join
    fut = concurrent.futures.Future
    _SAVED["future_set_result"] = fut.set_result
    _SAVED["future_set_exception"] = fut.set_exception
    _SAVED["future_result"] = fut.result
    _SAVED["future_exception"] = fut.exception
    fut.set_result = _tsan_future_set_result
    fut.set_exception = _tsan_future_set_exception
    fut.result = _tsan_future_result
    fut.exception = _tsan_future_exception
    _SAVED["executor_submit"] = concurrent.futures.ThreadPoolExecutor.submit
    concurrent.futures.ThreadPoolExecutor.submit = _tsan_executor_submit


def _remove_patches() -> None:
    if not _SAVED:
        return
    threading.Lock = _SAVED["lock"]
    threading.RLock = _SAVED["rlock"]
    threading.Thread.start = _SAVED["thread_start"]
    threading.Thread.join = _SAVED["thread_join"]
    fut = concurrent.futures.Future
    fut.set_result = _SAVED["future_set_result"]
    fut.set_exception = _SAVED["future_set_exception"]
    fut.result = _SAVED["future_result"]
    fut.exception = _SAVED["future_exception"]
    concurrent.futures.ThreadPoolExecutor.submit = _SAVED["executor_submit"]
    _SAVED.clear()


# ---------------------------------------------------------------------------
# Tracked classes
# ---------------------------------------------------------------------------

# cls -> (had own __setattr__, saved, had own __getattribute__, saved)
_TRACKED: dict[type, tuple[bool, Any, bool, Any]] = {}


def track_class(cls: type) -> None:
    """Install access-recording hooks on `cls` (and, via the MRO, every
    subclass that does not define its own).  Idempotent."""
    if cls in _TRACKED:
        return
    had_set = "__setattr__" in cls.__dict__
    saved_set = cls.__dict__.get("__setattr__")
    had_get = "__getattribute__" in cls.__dict__
    saved_get = cls.__dict__.get("__getattribute__")
    base_set = cls.__setattr__
    base_get = cls.__getattribute__

    def __setattr__(self, name, value, _orig=base_set):
        det = TSAN
        if det is not None:
            det.record_access(self, name, True)
        _orig(self, name, value)

    def __getattribute__(self, name, _orig=base_get):
        det = TSAN
        if det is not None and det.track_reads:
            det.record_read(self, name)
        return _orig(self, name)

    cls.__setattr__ = __setattr__
    cls.__getattribute__ = __getattribute__
    _TRACKED[cls] = (had_set, saved_set, had_get, saved_get)


def _untrack_all() -> None:
    for cls, (had_set, saved_set, had_get, saved_get) in _TRACKED.items():
        if had_set:
            cls.__setattr__ = saved_set
        else:
            try:
                del cls.__setattr__
            except AttributeError:  # pragma: no cover
                pass
        if had_get:
            cls.__getattribute__ = saved_get
        else:
            try:
                del cls.__getattribute__
            except AttributeError:  # pragma: no cover
                pass
    _TRACKED.clear()


def _resolve_tracked(paths: Iterable[str]) -> list[type]:
    out: list[type] = []
    for path in paths:
        mod_name, _, cls_name = path.rpartition(".")
        if not mod_name:
            continue
        try:
            mod = importlib.import_module(mod_name)
            cls = getattr(mod, cls_name)
        except Exception:  # noqa: BLE001 — optional deps may be absent
            continue
        if isinstance(cls, type):
            out.append(cls)
    return out


def _config_tracked_paths() -> list[str]:
    """pyproject [tool.openr-analysis] tsan_tracked_paths, falling back
    to the defaults.  Config failures fall back silently — arming must
    never crash the daemon it is auditing."""
    try:
        from pathlib import Path

        from .core import load_config

        cfg, _root = load_config(Path.cwd())
        if cfg.tsan_tracked_paths:
            return list(cfg.tsan_tracked_paths)
    except Exception:  # noqa: BLE001
        pass
    return list(DEFAULT_TRACKED_PATHS)


# ---------------------------------------------------------------------------
# Arming
# ---------------------------------------------------------------------------


def enable(
    tracked_paths: Optional[Iterable[str]] = None,
    suppressions: Optional[dict[tuple[str, str], str]] = None,
) -> RaceDetector:
    """Arm the detector: install lock/thread/future patches and tracked-
    class hooks, then publish the detector through the TSAN guard.
    Idempotent; returns the active detector."""
    global TSAN
    if TSAN is not None:
        return TSAN
    det = RaceDetector(suppressions=suppressions)
    _install_patches()
    paths = (
        list(tracked_paths)
        if tracked_paths is not None
        else _config_tracked_paths()
    )
    for cls in _resolve_tracked(paths):
        track_class(cls)
    TSAN = det
    return det


def disable() -> None:
    """Disarm: restore every patch and hook.  Proxy locks and wrapped
    closures created while armed keep working as passthroughs."""
    global TSAN
    if TSAN is None:
        return
    TSAN = None
    _untrack_all()
    _remove_patches()


def maybe_enable() -> Optional[RaceDetector]:
    """Env-gated arming seam: called from the pytest tsan_guard plumbing
    and OpenrDaemon.__init__; a no-op unless OPENR_TSAN=1 was set at
    import time."""
    if _ENV_ARMED and TSAN is None:
        enable()
    return TSAN
