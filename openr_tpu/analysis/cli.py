"""Command line front end: ``python -m openr_tpu.analysis [paths...]``.

Exit codes gate CI precisely:

- ``0`` — clean tree (no unsuppressed findings)
- ``1`` — findings: the tree is dirty, the analyzer worked
- ``2`` — the ANALYZER is broken or misused: bad paths, unreadable
  config/budget files, a program-auditor driver or trace failure, git
  unavailable for ``--changed-only``.  CI must treat 2 as infra failure,
  not as "findings" — a broken analyzer silently passing as rc=1 would
  hide the difference between "bugs found" and "nothing was checked".

``--programs`` adds the program-level rule family (imports jax, traces
every jit root + residency-ladder cell; see analysis/programs.py) on top
of the AST rules.  ``--write-budgets`` regenerates the op-count budget
file instead of reporting program-budget findings.

``--changed-only`` restricts *reported* AST findings to files touched in
the working tree (staged, unstaged or untracked, per ``git status``).
Analysis still runs over the full target set — the jit fixpoint, counter
cross-referencing and suppression audit are whole-tree properties, and
scoping the *analysis* would fabricate false positives (a counter seeded
in a changed file but bumped in an unchanged one).  Program rules are
whole-program by construction, so their findings always survive the
filter; so do the lock-discipline families (``lock-order``,
``guarded-by``, ``thread-shutdown-order``) — a cycle through the
whole-tree lock graph or a shutdown-order hole can anchor to an
unchanged file that an edit elsewhere just made reachable.

``--races <pytest expr...>`` arms the OPENR_TSAN dynamic happens-before
detector (analysis/race.py) and runs the given pytest expressions in a
subprocess; the tsan_guard fixture fails any test whose run produced an
unsuppressed race, so the usual exit-code contract holds (0 clean,
1 findings, 2 infra failure).

``--sched`` runs the deterministic schedule explorer (analysis/sched.py)
over the scenario library: exhaustive DPOR with certificate on the
smallest scenarios, POS sampling on the rest, under OPENR_SCHED_BUDGET_S.
``--sched-replay <id>`` re-executes one schedule bit-identically;
``--sched-shrink <id>`` ddmin-minimizes a failing schedule's choice
string.  Setting OPENR_SCHED=1 in the environment implies ``--sched``.
Same exit-code contract: 0 clean, 1 failing schedules, 2 infra failure.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from pathlib import Path

from .core import ALL_RULES, AnalysisError, load_config, run_analysis


def _changed_files(root: Path) -> set[str]:
    """Repo-relative posix paths of files touched in the working tree."""
    try:
        proc = subprocess.run(
            ["git", "-C", str(root), "status", "--porcelain"],
            capture_output=True,
            text=True,
            timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        raise AnalysisError(f"--changed-only needs git: {e}") from e
    if proc.returncode != 0:
        raise AnalysisError(
            "--changed-only needs a git work tree: "
            f"git status failed: {proc.stderr.strip()}"
        )
    changed: set[str] = set()
    for line in proc.stdout.splitlines():
        if len(line) < 4:
            continue
        path = line[3:]
        # renames report "old -> new"; the new path is the analyzable one
        if " -> " in path:
            path = path.split(" -> ", 1)[1]
        path = path.strip().strip('"')
        changed.add(Path(path).as_posix())
    return changed


def _run_races(exprs: list[str]) -> int:
    """Arm OPENR_TSAN and run pytest over `exprs` in a subprocess (the
    detector monkeypatches threading/futures — that must happen in a fresh
    interpreter, before the tests' objects exist)."""
    env = dict(os.environ)
    env["OPENR_TSAN"] = "1"
    env.setdefault("JAX_PLATFORMS", "cpu")
    cmd = [sys.executable, "-m", "pytest", "-q", *exprs]
    try:
        proc = subprocess.run(cmd, env=env)
    except OSError as e:
        print(f"error: --races could not launch pytest: {e}", file=sys.stderr)
        return 2
    if proc.returncode == 0:
        return 0
    if proc.returncode == 1:
        return 1  # test failures, incl. tsan_guard race findings
    # collection error, usage error, interrupted, ... -> infra failure
    return 2


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m openr_tpu.analysis",
        description=(
            "openr-tpu static invariant checker: jit hygiene, thread "
            "discipline, counter hygiene, and (with --programs) "
            "program-level jaxpr contracts"
        ),
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=["openr_tpu"],
        help="files or directories to analyze (default: openr_tpu)",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text", dest="fmt"
    )
    parser.add_argument(
        "--show-suppressed",
        action="store_true",
        help="also print findings silenced by '# openr: disable=' markers",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rule ids and exit"
    )
    parser.add_argument(
        "--programs",
        action="store_true",
        help=(
            "also run the program-level auditor (imports jax; traces every "
            "jit root and residency-ladder cell on CPU)"
        ),
    )
    parser.add_argument(
        "--write-budgets",
        action="store_true",
        help=(
            "regenerate openr_tpu/analysis/program_budgets.json from the "
            "measured op counts instead of reporting program-budget "
            "findings (implies --programs)"
        ),
    )
    parser.add_argument(
        "--changed-only",
        action="store_true",
        help=(
            "report AST findings only for files touched in the git working "
            "tree; program-* and lock-discipline findings are always "
            "whole-tree"
        ),
    )
    parser.add_argument(
        "--races",
        nargs="+",
        metavar="TEST_EXPR",
        help=(
            "run the given pytest expressions under OPENR_TSAN=1 (dynamic "
            "happens-before race detection); any unsuppressed race fails "
            "the run with exit code 1"
        ),
    )
    parser.add_argument(
        "--sched",
        action="store_true",
        help=(
            "run the deterministic schedule explorer over the scenario "
            "library (DPOR + POS sampling under OPENR_SCHED_BUDGET_S); "
            "OPENR_SCHED=1 in the environment implies this flag"
        ),
    )
    parser.add_argument(
        "--sched-replay",
        metavar="SCHEDULE_ID",
        help=(
            "re-execute one schedule bit-identically from its id "
            "(scenario[+plant]:s<seed>:<c0.c1...>); implies --sched"
        ),
    )
    parser.add_argument(
        "--sched-shrink",
        metavar="SCHEDULE_ID",
        help=(
            "ddmin-minimize a failing schedule's choice string to the "
            "shortest prefix-subsequence preserving the failure; "
            "implies --sched"
        ),
    )
    parser.add_argument(
        "--sched-seed",
        type=int,
        default=0,
        help="base seed for the --sched sampled-exploration passes",
    )
    args = parser.parse_args(argv)

    if args.races:
        return _run_races(args.races)

    if (
        args.sched
        or args.sched_replay
        or args.sched_shrink
        or os.environ.get("OPENR_SCHED", "") == "1"
    ):
        from . import sched as _sched

        return _sched.run_cli(args)

    if args.list_rules:
        for rule, desc in sorted(ALL_RULES.items()):
            print(f"{rule:28s} {desc}")
        return 0

    targets = [Path(p) for p in args.paths]
    missing = [p for p in targets if not p.exists()]
    if missing:
        print(
            f"error: no such path: {', '.join(map(str, missing))}",
            file=sys.stderr,
        )
        return 2

    programs = args.programs or args.write_budgets
    try:
        config, root = load_config(targets[0])
        changed = _changed_files(root) if args.changed_only else None
        reporter = run_analysis(
            targets,
            config,
            root,
            programs=programs,
            write_budgets=args.write_budgets,
        )
    except AnalysisError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    findings = reporter.sorted_findings()
    if changed is not None:
        # counter hygiene is cross-referenced whole-tree, so a serving.*
        # counter finding can anchor to an UNCHANGED file (e.g. a key
        # seeded in serving/ but orphaned by an edit elsewhere) — the
        # serving layer's SLO counters must never be filtered out of a
        # pre-commit pass
        _WHOLE_TREE_RULES = {"lock-order", "guarded-by", "thread-shutdown-order"}
        findings = [
            f
            for f in findings
            if f.rule.startswith("program-")
            or f.rule in _WHOLE_TREE_RULES
            or f.path in changed
            or (f.rule.startswith("counter-") and "serving." in f.message)
        ]

    if args.fmt == "json":
        print(
            json.dumps(
                {
                    "findings": [
                        {
                            "rule": f.rule,
                            "path": f.path,
                            "line": f.line,
                            "col": f.col,
                            "severity": f.severity.value,
                            "message": f.message,
                        }
                        for f in findings
                    ],
                    "suppressed": len(reporter.suppressed),
                },
                indent=2,
            )
        )
    else:
        for f in findings:
            print(f.format())
        if args.show_suppressed:
            for f in sorted(
                reporter.suppressed, key=lambda f: (f.path, f.line, f.col)
            ):
                print(f"(suppressed) {f.format()}")
        n = len(findings)
        print(
            f"{n} finding{'s' if n != 1 else ''} "
            f"({len(reporter.suppressed)} suppressed)"
        )
    return 1 if findings else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
