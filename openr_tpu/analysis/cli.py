"""Command line front end: ``python -m openr_tpu.analysis [paths...]``.

Exits nonzero when any unsuppressed finding remains, so it can gate CI.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .core import ALL_RULES, load_config, run_analysis


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m openr_tpu.analysis",
        description=(
            "openr-tpu static invariant checker: jit hygiene, thread "
            "discipline, counter hygiene"
        ),
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=["openr_tpu"],
        help="files or directories to analyze (default: openr_tpu)",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text", dest="fmt"
    )
    parser.add_argument(
        "--show-suppressed",
        action="store_true",
        help="also print findings silenced by '# openr: disable=' markers",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rule ids and exit"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(ALL_RULES.items()):
            print(f"{rule:28s} {desc}")
        return 0

    targets = [Path(p) for p in args.paths]
    missing = [p for p in targets if not p.exists()]
    if missing:
        print(
            f"error: no such path: {', '.join(map(str, missing))}",
            file=sys.stderr,
        )
        return 2

    config, root = load_config(targets[0])
    reporter = run_analysis(targets, config, root)
    findings = reporter.sorted_findings()

    if args.fmt == "json":
        print(
            json.dumps(
                {
                    "findings": [
                        {
                            "rule": f.rule,
                            "path": f.path,
                            "line": f.line,
                            "col": f.col,
                            "severity": f.severity.value,
                            "message": f.message,
                        }
                        for f in findings
                    ],
                    "suppressed": len(reporter.suppressed),
                },
                indent=2,
            )
        )
    else:
        for f in findings:
            print(f.format())
        if args.show_suppressed:
            for f in sorted(
                reporter.suppressed, key=lambda f: (f.path, f.line, f.col)
            ):
                print(f"(suppressed) {f.format()}")
        n = len(findings)
        print(
            f"{n} finding{'s' if n != 1 else ''} "
            f"({len(reporter.suppressed)} suppressed)"
        )
    return 1 if findings else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
