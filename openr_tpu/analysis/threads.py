"""Thread-discipline checkers.

Open/R's module invariant: each module (an ``OpenrEventBase`` subclass)
owns its state on its own thread + asyncio loop; modules communicate only
through ``RWQueue`` / ``ReplicateQueue`` streams or the ctrl handler's
``run_in_event_base_thread`` RPC seam.  Two rules enforce the static part:

- ``thread-cross-module-write``: an attribute *write* whose base is a
  module handle (``self.kvstore.x = ...`` or a local named after a module
  handle) from code outside that module's own class.  Reads are allowed —
  plenty of code inspects counters — but a write from another thread races
  the owner loop.  Composition-root wiring (performed in ``main.py`` before
  the module threads start) is expected to carry an explicit suppression.
- ``thread-queue-registration``: every ``ReplicateQueue``/``RWQueue``
  created on the daemon in ``main.py`` must be registered in the named
  ``self._queues`` dict — that dict is the introspection surface
  (``queue.<name>.*`` counters, drain-on-shutdown, chaos hooks); an
  unregistered queue is invisible to all three.

Three lock-discipline rules back the OPENR_TSAN dynamic detector
(``analysis/race.py``) with whole-tree static evidence:

- ``lock-order``: build the whole-tree lock graph (node = ``Class.attr``
  of a ``self.X = Lock()/RLock()/Condition()`` site; ``Condition(self._y)``
  aliases to ``_y``'s node; edge = inner acquisition while an outer is
  held) and flag every edge that sits on a cycle — an inconsistent
  acquisition order is a deadlock waiting for one unlucky schedule.
  ``lock_order_exclude`` in config drops known-hierarchical nodes.
- ``guarded-by``: within one class, an attribute written under
  ``with self.<lock>`` at one site and bare at another (outside
  ``__init__``) — the lock protects nothing if any writer skips it.
- ``thread-shutdown-order``: in classes carrying the ``self._queues``
  registry, every queue with a consumer (a module constructed with
  ``self.Q.get_reader()``) must be closed in ``stop()`` *before* that
  consumer's ``stop()`` — otherwise shutdown can wedge on a ``get()``
  nobody will ever wake.  Today only convention enforces this ordering.

One liveness rule guards the event-base loops themselves:

- ``blocking-call-in-eventbase``: an unbounded blocking call —
  ``time.sleep``, ``Future.result()`` with no timeout, ``Queue.get()``
  with no timeout — inside code that runs ON a module's event-base
  thread: any ``async def`` body (fiber tasks run on the loop) or any
  callable marshalled via ``run_in_event_base_thread`` /
  ``call_soon_threadsafe`` / ``schedule_timeout``.  Context propagates
  through the intra-file call graph (``self.helper()`` / ``helper()``),
  so a blocking call buried two helpers deep is still flagged.  One such
  call parks the whole loop: every fiber, timer and heartbeat on that
  module stalls until it returns — the watchdog fires on exactly this.
"""

from __future__ import annotations

import ast
from pathlib import Path

from .core import AnalysisConfig, Reporter, SourceFile

_QUEUE_CLASSES = {"ReplicateQueue", "RWQueue"}

#: default module-handle attribute names (overridable via config)
DEFAULT_MODULE_ATTRS = [
    "kvstore",
    "decision",
    "fib",
    "link_monitor",
    "spark",
    "monitor",
    "prefix_manager",
    "ctrl_server",
    "thrift_shim",
    "netlink",
    "watchdog",
    "serving",
    # post-PR-13 serving surface: the coalescing scheduler, the replica
    # front door, and the fleet's replica handles / front-door handler
    "scheduler",
    "router",
    "handler",
    "daemons",
]


def _class_owns_attr(class_name: str, attr: str) -> bool:
    """`KvStore` owns `kvstore`, `LinkMonitor` owns `link_monitor`, ..."""
    snake = "".join(
        ("_" + c.lower()) if c.isupper() else c for c in class_name
    ).lstrip("_")
    return snake == attr or class_name.lower() == attr.replace("_", "")


def check(
    files: list[SourceFile],
    reporter: Reporter,
    config: AnalysisConfig,
    root: Path,
) -> None:
    module_attrs = set(config.module_attrs or DEFAULT_MODULE_ATTRS)
    lock_edges: list[tuple[str, str, SourceFile, ast.AST]] = []
    for sf in files:
        _check_cross_module_writes(sf, reporter, module_attrs)
        # self-gates on the presence of a `self._queues = {...}` registry
        _check_queue_registration(sf, reporter)
        _check_guarded_by(sf, reporter)
        _check_shutdown_order(sf, reporter)
        _check_blocking_in_eventbase(sf, reporter)
        lock_edges.extend(_collect_lock_edges(sf))
    _check_lock_order(lock_edges, reporter, set(config.lock_order_exclude))


def _check_cross_module_writes(
    sf: SourceFile, reporter: Reporter, module_attrs: set[str]
) -> None:
    class_stack: list[str] = []

    def visit(node: ast.AST) -> None:
        if isinstance(node, ast.ClassDef):
            class_stack.append(node.name)
            for child in node.body:
                visit(child)
            class_stack.pop()
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for tgt in targets:
                _check_target(tgt, node)
        for child in ast.iter_child_nodes(node):
            visit(child)

    def _check_target(tgt: ast.AST, stmt: ast.stmt) -> None:
        if not isinstance(tgt, ast.Attribute):
            # `self.kvstore.counters["x"] = 1` has a Subscript target whose
            # value chain still bottoms out in a module handle
            if isinstance(tgt, ast.Subscript):
                _check_target_base(tgt.value, None, stmt)
            return
        _check_target_base(tgt.value, tgt.attr, stmt)

    def _check_target_base(
        base: ast.AST, attr: str | None, stmt: ast.stmt
    ) -> None:
        # Find a module handle anywhere along the base chain, so both
        # `self.kvstore.x = ...` and `self.kvstore.counters["x"] = ...`
        # (a Subscript target) are caught.
        handle: str | None = None
        cur = base
        while isinstance(cur, (ast.Attribute, ast.Subscript)):
            if (
                isinstance(cur, ast.Attribute)
                and isinstance(cur.value, ast.Name)
                and cur.value.id == "self"
                and cur.attr in module_attrs
            ):
                handle = cur.attr
                break
            cur = cur.value
        if handle is None and isinstance(cur, ast.Name) and cur.id in module_attrs:
            handle = cur.id
        if handle is None:
            return
        if class_stack and _class_owns_attr(class_stack[-1], handle):
            return
        what = f".{attr}" if attr else "[...]"
        reporter.emit(
            sf,
            "thread-cross-module-write",
            stmt,
            f"write to `{handle}{what}` crosses a module-thread boundary; "
            "modules own their state — communicate through a queue or "
            "run_in_event_base_thread (pre-start wiring in the composition "
            "root should carry an explicit suppression)",
        )

    visit(sf.tree)


def _check_queue_registration(sf: SourceFile, reporter: Reporter) -> None:
    for cls in ast.walk(sf.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        created: dict[str, ast.stmt] = {}
        registered: set[str] = set()
        has_registry = False
        for node in ast.walk(cls):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign) else [node.target]
                )
                value = node.value
                if value is None:
                    continue
                for tgt in targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        if _creates_queue(value):
                            created[tgt.attr] = node
                        if tgt.attr == "_queues" and isinstance(value, ast.Dict):
                            has_registry = True
                            for v in value.values:
                                if (
                                    isinstance(v, ast.Attribute)
                                    and isinstance(v.value, ast.Name)
                                    and v.value.id == "self"
                                ):
                                    registered.add(v.attr)
        if not has_registry:
            continue
        for attr, node in sorted(created.items()):
            if attr not in registered:
                reporter.emit(
                    sf,
                    "thread-queue-registration",
                    node,
                    f"queue `self.{attr}` is not registered in the named "
                    "`self._queues` dict; unregistered queues are invisible "
                    "to queue.<name>.* counters, shutdown drain, and chaos "
                    "hooks",
                )


def _creates_queue(value: ast.AST) -> bool:
    """True for `ReplicateQueue(...)` and `injected or ReplicateQueue(...)`."""
    if isinstance(value, ast.BoolOp):
        return any(_creates_queue(v) for v in value.values)
    return (
        isinstance(value, ast.Call)
        and _call_class_name(value) in _QUEUE_CLASSES
    )


def _call_class_name(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


# ---------------------------------------------------------------------------
# Lock-discipline rules (static companions to the OPENR_TSAN detector)
# ---------------------------------------------------------------------------

_LOCK_CLASSES = {"Lock", "RLock", "Condition"}


def _self_attr(node: ast.AST) -> str | None:
    """`self.X` -> `X`, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _class_lock_attrs(cls: ast.ClassDef) -> dict[str, str]:
    """Lock-holding attrs of a class: {attr: canonical attr}.  A
    ``Condition(self._y)`` shares ``_y``'s underlying lock, so its attr
    aliases to ``_y``'s node in the lock graph."""
    locks: dict[str, str] = {}
    aliases: dict[str, str] = {}
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        if not isinstance(value, ast.Call):
            continue
        name = _call_class_name(value)
        if name not in _LOCK_CLASSES:
            continue
        for tgt in node.targets:
            attr = _self_attr(tgt)
            if attr is None:
                continue
            locks[attr] = attr
            if name == "Condition" and value.args:
                inner = _self_attr(value.args[0])
                if inner is not None:
                    aliases[attr] = inner
    for attr, inner in aliases.items():
        if inner in locks:
            locks[attr] = inner
    return locks


def _iter_class_functions(cls: ast.ClassDef):
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _collect_lock_edges(
    sf: SourceFile,
) -> list[tuple[str, str, SourceFile, ast.AST]]:
    """Whole-tree lock-graph edges for one file: (held_node, inner_node,
    file, site) for every acquisition of `inner` while `held` is held.
    Node names are `Class.attr` with Condition aliasing applied."""
    edges: list[tuple[str, str, SourceFile, ast.AST]] = []
    for cls in ast.walk(sf.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        locks = _class_lock_attrs(cls)
        if not locks:
            continue

        def node_name(attr: str) -> str:
            return f"{cls.name}.{locks[attr]}"

        def walk(node: ast.AST, held: list[str]) -> None:
            if isinstance(node, ast.With):
                acquired: list[str] = []
                for item in node.items:
                    ctx = item.context_expr
                    attr = _self_attr(ctx)
                    if attr is not None and attr in locks:
                        # `with self._a, self._b:` acquires _b while _a
                        # is already held — same edge as nesting
                        _edge(attr, node, held + acquired)
                        acquired.append(attr)
                for child in node.body:
                    walk(child, held + acquired)
                return
            if isinstance(node, ast.Call):
                # explicit self.X.acquire() while something is held: edge
                # only (scope of the manual hold is not tracked)
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr == "acquire":
                    attr = _self_attr(f.value)
                    if attr is not None and attr in locks:
                        _edge(attr, node, held)
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        def _edge(attr: str, site: ast.AST, held: list[str]) -> None:
            inner = node_name(attr)
            for h in held:
                outer = node_name(h)
                if outer != inner:
                    edges.append((outer, inner, sf, site))

        for fn in _iter_class_functions(cls):
            walk(fn, [])
    return edges


def _check_lock_order(
    edges: list[tuple[str, str, SourceFile, ast.AST]],
    reporter: Reporter,
    exclude: set[str],
) -> None:
    edges = [
        (a, b, sf, site)
        for (a, b, sf, site) in edges
        if a not in exclude and b not in exclude
    ]
    adj: dict[str, set[str]] = {}
    for a, b, _sf, _site in edges:
        adj.setdefault(a, set()).add(b)

    def reaches(src: str, dst: str) -> bool:
        seen = {src}
        stack = [src]
        while stack:
            cur = stack.pop()
            if cur == dst:
                return True
            for nxt in adj.get(cur, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
        return False

    reverse_sites: dict[tuple[str, str], tuple[SourceFile, ast.AST]] = {}
    for a, b, sf, site in edges:
        reverse_sites.setdefault((a, b), (sf, site))
    for a, b, sf, site in edges:
        if not reaches(b, a):
            continue
        counter = reverse_sites.get((b, a))
        if counter is not None:
            csf, csite = counter
            where = f"{csf.rel}:{getattr(csite, 'lineno', '?')}"
            detail = f"the reverse order `{b}` -> `{a}` is taken at {where}"
        else:
            detail = (
                f"`{b}` reaches back to `{a}` through the whole-tree lock "
                "graph"
            )
        reporter.emit(
            sf,
            "lock-order",
            site,
            f"lock `{b}` acquired while holding `{a}`, but {detail}; "
            "inconsistent acquisition order deadlocks on the schedule "
            "where both threads hold their first lock",
        )


def _check_guarded_by(sf: SourceFile, reporter: Reporter) -> None:
    for cls in ast.walk(sf.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        locks = _class_lock_attrs(cls)
        if not locks:
            continue
        # attr -> list of (held_locks_at_write, site)
        writes: dict[str, list[tuple[frozenset[str], ast.AST]]] = {}

        def walk(node: ast.AST, held: frozenset[str]) -> None:
            if isinstance(node, ast.With):
                acquired = set()
                for item in node.items:
                    attr = _self_attr(item.context_expr)
                    if attr is not None and attr in locks:
                        acquired.add(locks[attr])
                for child in node.body:
                    walk(child, held | acquired)
                return
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for tgt in targets:
                    # Subscript writes (counters["x"] = 1) mutate through a
                    # stable container reference, not the attribute binding
                    attr = _self_attr(tgt)
                    if attr is not None and attr not in locks:
                        writes.setdefault(attr, []).append((held, node))
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        for fn in _iter_class_functions(cls):
            if fn.name == "__init__":
                continue  # construction happens-before every other thread
            walk(fn, frozenset())

        for attr, sites in sorted(writes.items()):
            guarded = [s for s in sites if s[0]]
            bare = [s for s in sites if not s[0]]
            if not guarded or not bare:
                continue
            glocks = sorted(guarded[0][0])
            gline = getattr(guarded[0][1], "lineno", "?")
            for _held, node in bare:
                reporter.emit(
                    sf,
                    "guarded-by",
                    node,
                    f"`self.{attr}` written bare here but under "
                    f"`{'`/`'.join(glocks)}` at line {gline}; a lock only "
                    "protects state if every writer takes it",
                )


def _check_shutdown_order(sf: SourceFile, reporter: Reporter) -> None:
    """Queues in the `self._queues` registry must close before the modules
    consuming them stop."""
    for cls in ast.walk(sf.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        registered = _registered_queue_attrs(cls)
        if not registered:
            continue
        consumers = _queue_consumers(cls, registered)
        if not consumers:
            continue
        stop_fn = next(
            (f for f in _iter_class_functions(cls) if f.name == "stop"), None
        )
        if stop_fn is None:
            continue
        close_lines, stop_lines = _stop_method_events(cls, stop_fn, registered)
        for module, (queues, _site) in sorted(consumers.items()):
            mod_stop = stop_lines.get(module)
            if mod_stop is None:
                continue
            for q in sorted(queues):
                q_close = close_lines.get(q)
                if q_close is None:
                    reporter.emit(
                        sf,
                        "thread-shutdown-order",
                        (mod_stop, 0),
                        f"`self.{module}.stop()` but its input queue "
                        f"`self.{q}` is never closed in stop(); the "
                        "consumer can wedge on a get() nobody will wake",
                    )
                elif q_close > mod_stop:
                    reporter.emit(
                        sf,
                        "thread-shutdown-order",
                        (mod_stop, 0),
                        f"`self.{module}.stop()` runs before `self.{q}` "
                        f"closes (line {q_close}); close/drain the queue "
                        "first so the consumer's final get() returns",
                    )


def _registered_queue_attrs(cls: ast.ClassDef) -> set[str]:
    registered: set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        value = node.value
        if value is None or not isinstance(value, ast.Dict):
            continue
        for tgt in targets:
            if _self_attr(tgt) == "_queues":
                for v in value.values:
                    attr = _self_attr(v)
                    if attr is not None:
                        registered.add(attr)
    return registered


def _queue_consumers(
    cls: ast.ClassDef, registered: set[str]
) -> dict[str, tuple[set[str], ast.AST]]:
    """Modules constructed with a `self.Q.get_reader()` argument:
    {module_attr: ({queue_attrs}, construction site)}."""
    consumers: dict[str, tuple[set[str], ast.AST]] = {}
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        if not isinstance(value, ast.Call):
            continue
        queues: set[str] = set()
        for sub in ast.walk(value):
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "get_reader"
            ):
                qattr = _self_attr(sub.func.value)
                if qattr is not None and qattr in registered:
                    queues.add(qattr)
        if not queues:
            continue
        for tgt in node.targets:
            mattr = _self_attr(tgt)
            if mattr is not None:
                prev = consumers.get(mattr)
                if prev is not None:
                    prev[0].update(queues)
                else:
                    consumers[mattr] = (queues, node)
    return consumers


def _stop_method_events(
    cls: ast.ClassDef, stop_fn: ast.AST, registered: set[str]
) -> tuple[dict[str, int], dict[str, int]]:
    """(queue close lines, module stop lines) observed in stop().

    Recognizes the close-all loop `for q in self._queues.values():
    q.close()` (closes every registered queue at that line), per-queue
    `self.Q.close()`, direct `self.M.stop()`, and the gather-then-stop
    idiom `modules = [self.A, ...]` + `for m in modules: m.stop()`."""
    close_lines: dict[str, int] = {}
    stop_lines: dict[str, int] = {}
    # Name -> list of self-attrs it holds (list-literal resolution)
    list_vars: dict[str, list[str]] = {}
    for node in ast.walk(stop_fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.List):
            attrs = [
                a
                for a in (_self_attr(el) for el in node.value.elts)
                if a is not None
            ]
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and attrs:
                    list_vars[tgt.id] = attrs
        if isinstance(node, ast.For):
            loop_attrs: list[str] | None = None
            close_all = False
            it = node.iter
            if (
                isinstance(it, ast.Call)
                and isinstance(it.func, ast.Attribute)
                and it.func.attr == "values"
                and _self_attr(it.func.value) == "_queues"
            ):
                close_all = True
            elif isinstance(it, ast.Name) and it.id in list_vars:
                loop_attrs = list_vars[it.id]
            if close_all or loop_attrs is not None:
                var = node.target.id if isinstance(node.target, ast.Name) else None
                for sub in ast.walk(node):
                    if not (
                        isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and isinstance(sub.func.value, ast.Name)
                        and sub.func.value.id == var
                    ):
                        continue
                    if close_all and sub.func.attr == "close":
                        for q in registered:
                            close_lines.setdefault(q, node.lineno)
                    if loop_attrs is not None and sub.func.attr == "stop":
                        for m in loop_attrs:
                            stop_lines.setdefault(m, node.lineno)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            owner = _self_attr(node.func.value)
            if owner is None:
                continue
            if node.func.attr == "close" and owner in registered:
                close_lines.setdefault(owner, node.lineno)
            if node.func.attr == "stop":
                stop_lines.setdefault(owner, node.lineno)
    return close_lines, stop_lines


# ---------------------------------------------------------------------------
# blocking-call-in-eventbase: loop-liveness rule
# ---------------------------------------------------------------------------

#: APIs whose callable arguments execute on a module's event-base thread
_MARSHAL_APIS = {
    "run_in_event_base_thread",
    "call_soon_threadsafe",
    "schedule_timeout",
}


def _iter_own_body(fn: ast.AST):
    """Yield nodes of a function body excluding nested def/class bodies
    (those are separate call-graph nodes); lambdas are included — a
    lambda closed over in a reachable body runs in the same context."""
    body = getattr(fn, "body", fn)
    stack = list(body) if isinstance(body, list) else [body]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _local_shadows(fn: ast.AST) -> set[str]:
    """Names a function rebinds locally (parameters, assignments, local
    import aliases): calls through them must NOT resolve to same-named
    defs elsewhere in the file (`from x import what_if as run` would
    otherwise alias the module's `run` method into the call graph)."""
    shadows: set[str] = set()
    args = getattr(fn, "args", None)
    if args is not None:
        for a in list(args.args) + list(args.posonlyargs) + list(args.kwonlyargs):
            shadows.add(a.arg)
        if args.vararg:
            shadows.add(args.vararg.arg)
        if args.kwarg:
            shadows.add(args.kwarg.arg)
    for node in _iter_own_body(fn):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                shadows.add(alias.asname or alias.name.split(".")[0])
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    shadows.add(tgt.id)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            if isinstance(node.target, ast.Name):
                shadows.add(node.target.id)
    return shadows


def _awaited_calls(fn: ast.AST) -> set[int]:
    """ids of Call nodes directly under an `await`: those suspend the
    coroutine instead of blocking the loop (asyncio.Queue.get() vs
    queue.Queue.get())."""
    out: set[int] = set()
    for node in _iter_own_body(fn):
        if isinstance(node, ast.Await) and isinstance(node.value, ast.Call):
            out.add(id(node.value))
    return out


def _timeout_unbounded(call: ast.Call) -> bool:
    """True when the call can block forever: `.result()`, `.get()`,
    `.result(None)`, `.get(timeout=None)`.  A variable timeout is given
    the benefit of the doubt — only literal-None / absent is flagged."""
    if call.args:
        return len(call.args) == 1 and (
            isinstance(call.args[0], ast.Constant) and call.args[0].value is None
        )
    for kw in call.keywords:
        if kw.arg == "timeout":
            return isinstance(kw.value, ast.Constant) and kw.value.value is None
    return True


def _blocking_call(call: ast.Call, sleep_names: set[str]) -> str | None:
    f = call.func
    if isinstance(f, ast.Name) and f.id in sleep_names:
        return "time.sleep()"
    if not isinstance(f, ast.Attribute):
        return None
    if (
        f.attr == "sleep"
        and isinstance(f.value, ast.Name)
        and f.value.id == "time"
    ):
        return "time.sleep()"
    if f.attr == "result" and _timeout_unbounded(call):
        return "Future.result() with no timeout"
    if f.attr == "get" and not call.args and _timeout_unbounded(call):
        # zero-positional-arg .get(): the queue idiom (dict.get takes a
        # key); a bounded .get(timeout=5) passes
        return "Queue.get() with no timeout"
    return None


def _check_blocking_in_eventbase(sf: SourceFile, reporter: Reporter) -> None:
    # `from time import sleep` makes the bare name a blocking call too
    sleep_names: set[str] = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name == "sleep":
                    sleep_names.add(alias.asname or alias.name)

    # every def in the file (any nesting), with its enclosing class
    defs: list[tuple[ast.AST, str | None]] = []

    def collect(node: ast.AST, cls: str | None) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                collect(child, child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.append((child, cls))
                collect(child, cls)
            else:
                collect(child, cls)

    collect(sf.tree, None)
    by_name: dict[str, list[ast.AST]] = {}
    for fn, _cls in defs:
        by_name.setdefault(fn.name, []).append(fn)

    # roots: async defs (fiber tasks run on the loop) + callables handed
    # to the cross-thread marshal APIs; lambdas handed directly are
    # scanned in place
    reason: dict[int, str] = {}  # id(fn) -> why it runs on the loop
    queue: list[ast.AST] = []
    lambda_roots: list[tuple[ast.Lambda, str]] = []
    for fn, _cls in defs:
        if isinstance(fn, ast.AsyncFunctionDef):
            reason[id(fn)] = f"fiber task `{fn.name}`"
            queue.append(fn)
    for node in ast.walk(sf.tree):
        if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
            continue
        api = node.func.attr
        if api not in _MARSHAL_APIS:
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Lambda):
                lambda_roots.append((arg, api))
                continue
            cb: str | None = None
            if isinstance(arg, ast.Name):
                cb = arg.id
            elif isinstance(arg, ast.Attribute) and _self_attr(arg) is not None:
                cb = arg.attr
            if cb is None:
                continue
            for fn in by_name.get(cb, ()):
                if id(fn) not in reason:
                    reason[id(fn)] = f"callback passed to {api}()"
                    queue.append(fn)

    # propagate through the intra-file call graph: `helper()` and
    # `self.helper()` from a loop-context body put `helper` on the loop
    while queue:
        fn = queue.pop()
        why = reason[id(fn)]
        shadows = _local_shadows(fn)
        for node in _iter_own_body(fn):
            if not isinstance(node, ast.Call):
                continue
            callee: str | None = None
            if isinstance(node.func, ast.Name) and node.func.id not in shadows:
                callee = node.func.id
            elif isinstance(node.func, ast.Attribute) and _self_attr(node.func):
                callee = node.func.attr
            if callee is None:
                continue
            for target in by_name.get(callee, ()):
                if id(target) not in reason:
                    reason[id(target)] = f"`{target.name}` called from {why}"
                    queue.append(target)

    def scan(body_owner: ast.AST, why: str) -> None:
        awaited = _awaited_calls(body_owner)
        for node in _iter_own_body(body_owner):
            if not isinstance(node, ast.Call) or id(node) in awaited:
                continue
            what = _blocking_call(node, sleep_names)
            if what is not None:
                reporter.emit(
                    sf,
                    "blocking-call-in-eventbase",
                    node,
                    f"blocking {what} runs on a module event-base thread "
                    f"({why}); one blocked callback parks the loop — every "
                    "fiber, timer and heartbeat on that module stalls.  "
                    "Use await/aget(), a bounded timeout, or marshal the "
                    "wait onto a worker thread",
                )

    for fn, _cls in defs:
        if id(fn) in reason:
            scan(fn, reason[id(fn)])
    for lam, api in lambda_roots:
        scan(lam, f"lambda passed to {api}()")
