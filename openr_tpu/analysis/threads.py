"""Thread-discipline checkers.

Open/R's module invariant: each module (an ``OpenrEventBase`` subclass)
owns its state on its own thread + asyncio loop; modules communicate only
through ``RWQueue`` / ``ReplicateQueue`` streams or the ctrl handler's
``run_in_event_base_thread`` RPC seam.  Two rules enforce the static part:

- ``thread-cross-module-write``: an attribute *write* whose base is a
  module handle (``self.kvstore.x = ...`` or a local named after a module
  handle) from code outside that module's own class.  Reads are allowed —
  plenty of code inspects counters — but a write from another thread races
  the owner loop.  Composition-root wiring (performed in ``main.py`` before
  the module threads start) is expected to carry an explicit suppression.
- ``thread-queue-registration``: every ``ReplicateQueue``/``RWQueue``
  created on the daemon in ``main.py`` must be registered in the named
  ``self._queues`` dict — that dict is the introspection surface
  (``queue.<name>.*`` counters, drain-on-shutdown, chaos hooks); an
  unregistered queue is invisible to all three.
"""

from __future__ import annotations

import ast
from pathlib import Path

from .core import AnalysisConfig, Reporter, SourceFile

_QUEUE_CLASSES = {"ReplicateQueue", "RWQueue"}

#: default module-handle attribute names (overridable via config)
DEFAULT_MODULE_ATTRS = [
    "kvstore",
    "decision",
    "fib",
    "link_monitor",
    "spark",
    "monitor",
    "prefix_manager",
    "ctrl_server",
    "thrift_shim",
    "netlink",
    "watchdog",
    "serving",
]


def _class_owns_attr(class_name: str, attr: str) -> bool:
    """`KvStore` owns `kvstore`, `LinkMonitor` owns `link_monitor`, ..."""
    snake = "".join(
        ("_" + c.lower()) if c.isupper() else c for c in class_name
    ).lstrip("_")
    return snake == attr or class_name.lower() == attr.replace("_", "")


def check(
    files: list[SourceFile],
    reporter: Reporter,
    config: AnalysisConfig,
    root: Path,
) -> None:
    module_attrs = set(config.module_attrs or DEFAULT_MODULE_ATTRS)
    for sf in files:
        _check_cross_module_writes(sf, reporter, module_attrs)
        # self-gates on the presence of a `self._queues = {...}` registry
        _check_queue_registration(sf, reporter)


def _check_cross_module_writes(
    sf: SourceFile, reporter: Reporter, module_attrs: set[str]
) -> None:
    class_stack: list[str] = []

    def visit(node: ast.AST) -> None:
        if isinstance(node, ast.ClassDef):
            class_stack.append(node.name)
            for child in node.body:
                visit(child)
            class_stack.pop()
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for tgt in targets:
                _check_target(tgt, node)
        for child in ast.iter_child_nodes(node):
            visit(child)

    def _check_target(tgt: ast.AST, stmt: ast.stmt) -> None:
        if not isinstance(tgt, ast.Attribute):
            # `self.kvstore.counters["x"] = 1` has a Subscript target whose
            # value chain still bottoms out in a module handle
            if isinstance(tgt, ast.Subscript):
                _check_target_base(tgt.value, None, stmt)
            return
        _check_target_base(tgt.value, tgt.attr, stmt)

    def _check_target_base(
        base: ast.AST, attr: str | None, stmt: ast.stmt
    ) -> None:
        # Find a module handle anywhere along the base chain, so both
        # `self.kvstore.x = ...` and `self.kvstore.counters["x"] = ...`
        # (a Subscript target) are caught.
        handle: str | None = None
        cur = base
        while isinstance(cur, (ast.Attribute, ast.Subscript)):
            if (
                isinstance(cur, ast.Attribute)
                and isinstance(cur.value, ast.Name)
                and cur.value.id == "self"
                and cur.attr in module_attrs
            ):
                handle = cur.attr
                break
            cur = cur.value
        if handle is None and isinstance(cur, ast.Name) and cur.id in module_attrs:
            handle = cur.id
        if handle is None:
            return
        if class_stack and _class_owns_attr(class_stack[-1], handle):
            return
        what = f".{attr}" if attr else "[...]"
        reporter.emit(
            sf,
            "thread-cross-module-write",
            stmt,
            f"write to `{handle}{what}` crosses a module-thread boundary; "
            "modules own their state — communicate through a queue or "
            "run_in_event_base_thread (pre-start wiring in the composition "
            "root should carry an explicit suppression)",
        )

    visit(sf.tree)


def _check_queue_registration(sf: SourceFile, reporter: Reporter) -> None:
    for cls in ast.walk(sf.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        created: dict[str, ast.stmt] = {}
        registered: set[str] = set()
        has_registry = False
        for node in ast.walk(cls):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign) else [node.target]
                )
                value = node.value
                if value is None:
                    continue
                for tgt in targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        if _creates_queue(value):
                            created[tgt.attr] = node
                        if tgt.attr == "_queues" and isinstance(value, ast.Dict):
                            has_registry = True
                            for v in value.values:
                                if (
                                    isinstance(v, ast.Attribute)
                                    and isinstance(v.value, ast.Name)
                                    and v.value.id == "self"
                                ):
                                    registered.add(v.attr)
        if not has_registry:
            continue
        for attr, node in sorted(created.items()):
            if attr not in registered:
                reporter.emit(
                    sf,
                    "thread-queue-registration",
                    node,
                    f"queue `self.{attr}` is not registered in the named "
                    "`self._queues` dict; unregistered queues are invisible "
                    "to queue.<name>.* counters, shutdown drain, and chaos "
                    "hooks",
                )


def _creates_queue(value: ast.AST) -> bool:
    """True for `ReplicateQueue(...)` and `injected or ReplicateQueue(...)`."""
    if isinstance(value, ast.BoolOp):
        return any(_creates_queue(v) for v in value.values)
    return (
        isinstance(value, ast.Call)
        and _call_class_name(value) in _QUEUE_CLASSES
    )


def _call_class_name(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None
