"""KvStore: replicated CRDT store with flooding and full-sync.

Semantics are kept byte-exact with the reference where convergence depends
on it (SURVEY hard-parts):

- `merge_key_values` reproduces KvStore::mergeKeyValues
  (openr/kvstore/KvStore.cpp:263-418): version > originatorId > value bytes
  > ttlVersion tie-break chain.
- `compare_values` reproduces KvStore::compareValues (KvStore.cpp:426-458)
  including the -2 "unknown" result when a value is missing.
- 3-way full sync: initiator sends its hash dump; responder returns full
  values where it is better plus `tobe_updated_keys` where the initiator is
  better; initiator merges and sends the finalize set back
  (requestThriftPeerSync/processThriftSuccess/finalizeFullSync,
  KvStore.cpp:1380-1640; dumpDifference KvStore.cpp).
- Peer FSM: IDLE -PEER_ADD-> SYNCING -SYNC_RESP_RCVD-> INITIALIZED, any
  error -> IDLE with exponential backoff (getNextState, KvStore.cpp:1001).
- Flooding: merged deltas flood to INITIALIZED peers except the sender;
  loop prevention via the nodeIds trail; token-bucket rate limiting with
  publication buffering (floodPublication/bufferPublication,
  KvStore.cpp:1700+).
- TTL: countdown queue evicts keys whose originator stopped refreshing;
  expired keys are published locally only (cleanupTtlCountdownQueue).

The transport is pluggable: `InProcessTransport` wires N stores in one
process for clusterless multi-node tests (the KvStoreWrapper pattern,
openr/kvstore/KvStoreWrapper.h:31); the ctrl server provides the TCP
transport between real daemons.
"""

from __future__ import annotations

import asyncio
import enum
import hashlib
import heapq
import logging
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Iterable, Optional, Protocol

from ..runtime.eventbase import OpenrEventBase
from ..obs import trace as _trace
from ..runtime.queue import QueueClosedError, ReplicateQueue, RQueue
from ..types import (
    FloodTopoSetParams,
    KvStorePeerState,
    KvStoreSyncEvent,
    PeerEvent,
    PeerSpec,
    Publication,
    SptInfo,
    SptInfos,
    TTL_INFINITY,
    Value,
)
from ..utils.backoff import ExponentialBackoff
from .dual import DualNode, DualState

log = logging.getLogger(__name__)

# reference: Constants.h
INITIAL_BACKOFF_S = 0.064
MAX_BACKOFF_S = 8.0
PARALLEL_SYNC_LIMIT_INITIAL = 2
PARALLEL_SYNC_LIMIT_MAX = 32
TTL_THRESHOLD_S = 0.5  # Constants::kTtlThreshold (about-to-expire filter)
FLOOD_PENDING_PUBLICATION_S = 0.1  # Constants::kFloodPendingPublication
# DUAL over an unreliable per-request transport (the reference's ZMQ peer
# channel was reliable+ordered; ours is not, so we serialize per-peer and
# retry until delivery or peer removal, and reconcile with periodic
# re-assertion + anti-entropy syncs)
DUAL_SEND_RETRY_INITIAL_S = 0.25
DUAL_SEND_MAX_BACKOFF_S = 8.0
SPT_REASSERT_INTERVAL_S = 15.0
SPT_ANTI_ENTROPY_SYNC_S = 60.0
# bound on queued-but-unsent DUAL messages per peer: an unreachable peer
# must not accumulate tasks/messages without limit; oldest are dropped
# (peer_down/peer_up reconciles DUAL state on reconnect anyway)
DUAL_SEND_BACKLOG_MAX = 64


def generate_hash(version: int, originator_id: str, value: Optional[bytes]) -> int:
    """Deterministic 63-bit hash of (version, originatorId, value)
    (reference: generateHash, openr/common/Util.cpp).

    Single-shot construction (identical byte layout to the incremental
    form: version NUL originator NUL value): per-hash Python call count
    was ~70% of merge_key_values' cost at 10k-key publications."""
    data = b"%d\x00%s\x00" % (version, originator_id.encode())
    if value is not None:
        data += value
    return (
        int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(), "big")
        >> 1
    )


def compare_values(v1: Value, v2: Value) -> int:
    """1 if v1 better, -1 if v2 better, 0 same, -2 unknown
    (reference: KvStore::compareValues, KvStore.cpp:426-458)."""
    if v1.version != v2.version:
        return 1 if v1.version > v2.version else -1
    if v1.originator_id != v2.originator_id:
        return 1 if v1.originator_id > v2.originator_id else -1
    if v1.hash is not None and v2.hash is not None and v1.hash == v2.hash:
        if v1.ttl_version != v2.ttl_version:
            return 1 if v1.ttl_version > v2.ttl_version else -1
        return 0
    if v1.value is not None and v2.value is not None:
        if v1.value == v2.value:
            # same logical value: retain higher ttlVersion (the reference
            # reaches this via matching hashes; values compare equal here)
            if v1.ttl_version != v2.ttl_version:
                return 1 if v1.ttl_version > v2.ttl_version else -1
            return 0
        return 1 if v1.value > v2.value else -1
    return -2


class KvStoreFilters:
    """Key-prefix + originator filter (reference: KvStoreFilters,
    openr/kvstore/KvStore.h:71)."""

    def __init__(
        self,
        key_prefixes: Iterable[str] = (),
        originator_ids: Iterable[str] = (),
    ) -> None:
        self.key_prefixes = list(key_prefixes)
        self.originator_ids = set(originator_ids)

    def key_match(self, key: str, value: Value) -> bool:
        """OR semantics: match either list; empty filter matches all."""
        if not self.key_prefixes and not self.originator_ids:
            return True
        if self.key_prefixes and any(key.startswith(p) for p in self.key_prefixes):
            return True
        return bool(self.originator_ids) and value.originator_id in self.originator_ids

    def key_match_all(self, key: str, value: Value) -> bool:
        """AND semantics."""
        if self.key_prefixes and not any(
            key.startswith(p) for p in self.key_prefixes
        ):
            return False
        if self.originator_ids and value.originator_id not in self.originator_ids:
            return False
        return True


def merge_key_values(
    kv_store: dict[str, Value],
    key_vals: dict[str, Value],
    filters: Optional[KvStoreFilters] = None,
) -> dict[str, Value]:
    """Exact CRDT merge (reference: KvStore::mergeKeyValues,
    KvStore.cpp:263-418).  Mutates kv_store; returns the accepted delta."""
    kv_updates: dict[str, Value] = {}
    for key, value in key_vals.items():
        if filters is not None and not filters.key_match(key, value):
            continue
        if value.ttl_ms != TTL_INFINITY and value.ttl_ms <= 0:
            continue

        existing = kv_store.get(key)
        my_version = existing.version if existing is not None else 0
        new_version = value.version
        if new_version < my_version:
            continue

        update_all = False
        update_ttl = False
        if value.value is not None:
            if new_version > my_version:
                update_all = True
            elif value.originator_id > existing.originator_id:
                update_all = True
            elif value.originator_id == existing.originator_id:
                # deterministic winner when same (version, originator):
                # higher value bytes; equal value retains higher ttlVersion
                if existing.value is None or value.value > existing.value:
                    update_all = True
                elif value.value == existing.value:
                    if value.ttl_version > existing.ttl_version:
                        update_ttl = True
        if (
            value.value is None
            and existing is not None
            and value.version == existing.version
            and value.originator_id == existing.originator_id
            and value.ttl_version > existing.ttl_version
        ):
            update_ttl = True

        if not update_all and not update_ttl:
            continue

        if update_all:
            new_value = Value(
                version=value.version,
                originator_id=value.originator_id,
                value=value.value,
                ttl_ms=value.ttl_ms,
                ttl_version=value.ttl_version,
                hash=value.hash
                if value.hash is not None
                else generate_hash(value.version, value.originator_id, value.value),
            )
            kv_store[key] = new_value
        else:  # update_ttl
            existing.ttl_ms = value.ttl_ms
            existing.ttl_version = value.ttl_version

        kv_updates[key] = value
    return kv_updates


# ---------------------------------------------------------------------------
# Transport seam
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class KeyDumpParams:
    """Reference: thrift::KeyDumpParams (openr/if/Types.thrift)."""

    keys: list[str] = field(default_factory=list)  # key prefixes
    originator_ids: list[str] = field(default_factory=list)
    key_val_hashes: Optional[dict[str, Value]] = None  # 3-way sync digest


@dataclass(slots=True)
class KeySetParams:
    """Reference: thrift::KeySetParams."""

    key_vals: dict[str, Value] = field(default_factory=dict)
    node_ids: Optional[list[str]] = None
    flood_root_id: Optional[str] = None
    timestamp_ms: int = 0


class KvStoreTransport(Protocol):
    """How one store's area DB talks to a peer store (thrift in the
    reference, SURVEY §2.3)."""

    async def full_dump(
        self, peer: PeerSpec, area: str, params: KeyDumpParams
    ) -> Publication: ...

    async def key_set(
        self, peer: PeerSpec, area: str, params: KeySetParams
    ) -> None: ...

    async def dual_messages(self, peer: PeerSpec, area: str, msgs) -> None: ...

    async def flood_topo_set(
        self, peer: PeerSpec, area: str, params
    ) -> None: ...


class TransportError(RuntimeError):
    pass


class InProcessTransport:
    """N stores in one process; addressing by PeerSpec.peer_addr.

    Supports fault injection (partitions) for tests — the MockIoProvider
    pattern (openr/tests/mocks/MockIoProvider.h:41)."""

    def __init__(self) -> None:
        self._stores: dict[str, "KvStore"] = {}
        self._partitioned: set[frozenset[str]] = set()
        # seeded per-edge failure injector (chaos.KvChaosInjector duck
        # type: check(op, src, dst) raises TransportError on schedule)
        self._chaos = None

    def register(self, addr: str, store: "KvStore") -> None:
        self._stores[addr] = store

    def set_chaos(self, injector) -> None:
        self._chaos = injector

    def _chaos_check(self, op: str, src: str, dst: str) -> None:
        if self._chaos is not None:
            self._chaos.check(op, src, dst)

    def set_partitioned(self, a: str, b: str, partitioned: bool) -> None:
        key = frozenset((a, b))
        if partitioned:
            self._partitioned.add(key)
        else:
            self._partitioned.discard(key)

    def _target(self, caller_addr: str, peer: PeerSpec) -> "KvStore":
        store = self._stores.get(peer.peer_addr)
        if store is None or not store.is_running:
            raise TransportError(f"peer {peer.peer_addr} unreachable")
        if frozenset((caller_addr, peer.peer_addr)) in self._partitioned:
            raise TransportError(
                f"partition between {caller_addr} and {peer.peer_addr}"
            )
        return store

    def bind(self, addr: str) -> "_BoundInProcessTransport":
        return _BoundInProcessTransport(self, addr)


class _BoundInProcessTransport:
    def __init__(self, fabric: InProcessTransport, addr: str) -> None:
        self._fabric = fabric
        self.addr = addr

    async def full_dump(
        self, peer: PeerSpec, area: str, params: KeyDumpParams
    ) -> Publication:
        self._fabric._chaos_check("full_dump", self.addr, peer.peer_addr)
        store = self._fabric._target(self.addr, peer)
        return await asyncio.wrap_future(
            store.run_in_event_base_thread(
                lambda: store._db(area).process_full_dump_request(params)
            )
        )

    async def key_set(
        self, peer: PeerSpec, area: str, params: KeySetParams
    ) -> None:
        self._fabric._chaos_check("key_set", self.addr, peer.peer_addr)
        store = self._fabric._target(self.addr, peer)
        await asyncio.wrap_future(
            store.run_in_event_base_thread(
                lambda: store._db(area).process_key_set_request(params)
            )
        )

    async def dual_messages(self, peer: PeerSpec, area: str, msgs) -> None:
        store = self._fabric._target(self.addr, peer)
        await asyncio.wrap_future(
            store.run_in_event_base_thread(
                lambda: store._db(area).process_dual_messages(msgs)
            )
        )

    async def flood_topo_set(self, peer: PeerSpec, area: str, params) -> None:
        store = self._fabric._target(self.addr, peer)
        await asyncio.wrap_future(
            store.run_in_event_base_thread(
                lambda: store._db(area).process_flood_topo_set(params)
            )
        )


# ---------------------------------------------------------------------------
# TTL countdown
# ---------------------------------------------------------------------------


@dataclass(slots=True, order=True)
class TtlCountdownEntry:
    """Reference: TtlCountdownQueueEntry (KvStore.h:52-69)."""

    expiry_time: float
    key: str = field(compare=False)
    version: int = field(compare=False)
    ttl_version: int = field(compare=False)
    originator_id: str = field(compare=False)


class _TokenBucket:
    """Reference: folly::BasicTokenBucket used for flood rate limiting
    (KvStore.h:497, floodRate config)."""

    def __init__(self, rate: float, burst: float) -> None:
        self._rate = rate
        self._burst = burst
        self._tokens = burst
        self._last = time.monotonic()

    def consume(self, n: float = 1.0) -> bool:
        now = time.monotonic()
        self._tokens = min(self._burst, self._tokens + (now - self._last) * self._rate)
        self._last = now
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False


# ---------------------------------------------------------------------------
# Per-area DB
# ---------------------------------------------------------------------------


class KvStorePeerEvent(enum.IntEnum):
    PEER_ADD = 0
    SYNC_RESP_RCVD = 2
    THRIFT_API_ERROR = 3


_NEXT_STATE = {
    (KvStorePeerState.IDLE, KvStorePeerEvent.PEER_ADD): KvStorePeerState.SYNCING,
    (KvStorePeerState.IDLE, KvStorePeerEvent.THRIFT_API_ERROR): KvStorePeerState.IDLE,
    (
        KvStorePeerState.SYNCING,
        KvStorePeerEvent.SYNC_RESP_RCVD,
    ): KvStorePeerState.INITIALIZED,
    (KvStorePeerState.SYNCING, KvStorePeerEvent.THRIFT_API_ERROR): KvStorePeerState.IDLE,
    (
        KvStorePeerState.INITIALIZED,
        KvStorePeerEvent.SYNC_RESP_RCVD,
    ): KvStorePeerState.INITIALIZED,
    (
        KvStorePeerState.INITIALIZED,
        KvStorePeerEvent.THRIFT_API_ERROR,
    ): KvStorePeerState.IDLE,
}


def get_next_state(
    curr: KvStorePeerState, event: KvStorePeerEvent
) -> KvStorePeerState:
    """Reference: KvStoreDb::getNextState (KvStore.cpp:1001-1047)."""
    nxt = _NEXT_STATE.get((curr, event))
    assert nxt is not None, f"invalid transition {curr} x {event}"
    return nxt


@dataclass
class KvStorePeer:
    """Reference: KvStoreDb::KvStorePeer (KvStore.h:429-453)."""

    name: str
    spec: PeerSpec
    backoff: ExponentialBackoff
    in_flight: bool = False
    # keys flooded while this peer was not yet INITIALIZED; flushed on sync
    # completion.  The reference silently drops these (floodPublication skips
    # non-initialized peers and the full-sync digest was snapshotted at
    # request time), leaving a loss window that its deployments paper over
    # with KvStoreClientInternal persist-key refresh; we close it instead.
    pending_flood_keys: set[str] = field(default_factory=set)
    # FIFO lock held by the single outbox drainer so retries cannot
    # reorder an older message after a newer one
    send_lock: asyncio.Lock = field(default_factory=asyncio.Lock)
    # DUAL message backlog (bounded at DUAL_SEND_BACKLOG_MAX):
    # (send_once, failure_counter) entries drained in order
    outbox: deque = field(default_factory=deque)
    # topo-set coalescing: (root_id, all_roots) -> latest params.  A
    # retried older set for a root is superseded by the newest one
    # (idempotent child add/remove — final state is what matters), so an
    # unreachable peer holds at most one pending set per root.
    pending_topo_set: dict = field(default_factory=dict)
    # set while an anti-entropy reconciliation sync is in flight, so its
    # completion does not re-fire initialization signaling (see
    # anti_entropy_sync / process_sync_success)
    anti_entropy_pending: bool = False
    # set when the DUAL outbox overflowed while this peer stayed up: a
    # dropped message means our DUAL exchange with it is no longer
    # complete, so once the backlog drains the drainer bounces DUAL state
    # for this peer (advisor r3 — reconnect-time reconciliation alone
    # never fires for a slow-but-alive peer)
    dual_reconcile_needed: bool = False
    # whether this peer has ever spoken DUAL to us.  A flood-opt-disabled
    # peer never does, and must keep receiving full-mesh floods even once
    # our SPT is valid — otherwise a mixed-config mesh silently starves it.
    # (The reference assumes a uniform knob; its getFloodPeers comment
    # mentions "peers-who-does-not-support-dual" but no flag exists.)
    dual_seen: bool = False


class KvStoreDb:
    """One area's store (reference: KvStoreDb, KvStore.h:191).

    All methods run on the owning KvStore's event-base thread."""

    def __init__(self, store: "KvStore", area: str) -> None:
        self.store = store
        self.area = area
        self.kv: dict[str, Value] = {}
        self.peers: dict[str, KvStorePeer] = {}
        self._ttl_heap: list[TtlCountdownEntry] = []
        self._ttl_timer = None
        self._sync_timer = None
        self._parallel_sync_limit = PARALLEL_SYNC_LIMIT_INITIAL
        self._flood_limiter = (
            _TokenBucket(store.flood_rate[0], store.flood_rate[1])
            if store.flood_rate
            else None
        )
        # (flood_root_id, learned-from sender) -> buffered key names
        self._publication_buffer: dict[
            tuple[Optional[str], Optional[str]], set[str]
        ] = {}
        self._pending_flood_timer = None
        self._spt_reassert_timer = None
        self._anti_entropy_timer = None
        self.counters: dict[str, int] = {}
        # DUAL flood-topology (reference: KvStoreDb extends DualNode,
        # KvStore.h:191; hooks at :309 sendDualMessages and :337
        # processNexthopChange).  Composed rather than inherited.
        self.dual = DualNode(
            store.node_id,
            is_root=store.enable_flood_optimization and store.is_flood_root,
            send_dual_messages=self._send_dual_messages,
            process_nexthop_change=self._process_nexthop_change,
        )

    def _bump(self, counter: str, n: int = 1) -> None:
        self.counters[counter] = self.counters.get(counter, 0) + n

    # -- DUAL flood-topology --------------------------------------------------

    def _send_dual_messages(self, neighbor: str, msgs) -> bool:
        """DualNode I/O hook (reference: KvStoreDb::sendDualMessages,
        KvStore.cpp:3117)."""
        peer = self.peers.get(neighbor)
        if peer is None:
            log.warning("dual: no peer %s to send messages to", neighbor)
            return False
        self._bump("kvstore.dual.num_pkt_sent")
        self._dual_to_peer(peer, msgs)
        return True

    async def _drain_peer_outbox(self, peer: KvStorePeer) -> None:
        """Reliable+ordered delivery of the peer's queued DUAL traffic over
        the per-request transport: one drainer per peer (FIFO send_lock)
        prevents a retried older message landing after a newer one; retries
        continue (capped backoff) until delivery or until the peer
        registration is replaced/removed — at which point peer_down/peer_up
        reconciles DUAL state anyway.  Restores the delivery semantics the
        reference got from its ordered ZMQ peer channel, with a bounded
        backlog: new work enqueued while draining is picked up by the
        running drainer, so an unreachable peer holds at most
        DUAL_SEND_BACKLOG_MAX messages + one pending topo-set per root.

        INTENTIONAL reorder vs the reference's single FIFO channel:
        pending topo-sets are serviced ahead of queued DUAL messages.
        Topo-sets are idempotent FINAL-STATE registrations (child
        add/remove — processFloodTopoSet is state-independent in the
        reference too), so delivering one ahead of an older DUAL message
        cannot corrupt the exchange, and servicing them first keeps the
        SPT attach latency independent of DUAL backlog depth.  Starvation
        is bounded: topo-sets coalesce by (root, all_roots) key, so the
        map holds at most one entry per root and only sustained nexthop
        flapping could re-fill it — at which point attaching to the
        latest parent IS the priority."""
        if peer.send_lock.locked():
            return  # a drainer is running; it will see the new work
        async with peer.send_lock:
            delay = DUAL_SEND_RETRY_INITIAL_S
            failures = 0
            while self.peers.get(peer.name) is peer:
                if peer.pending_topo_set:
                    # oldest-first across roots (dict preserves insertion
                    # order, so an all-roots clear precedes later sets)
                    topo_key = next(iter(peer.pending_topo_set))
                    params = peer.pending_topo_set[topo_key]

                    async def send_once(params=params):
                        await self.store.transport.flood_topo_set(
                            peer.spec, self.area, params
                        )

                    def done(topo_key=topo_key, params=params):
                        # only clear if not superseded while in flight
                        if peer.pending_topo_set.get(topo_key) is params:
                            del peer.pending_topo_set[topo_key]

                    failure_counter = "kvstore.dual.num_topo_set_failure"
                elif peer.outbox:
                    entry = peer.outbox[0]
                    send_once, failure_counter = entry

                    def done(entry=entry):
                        # the in-flight head may have been dropped by a
                        # backlog overflow while we awaited the send: only
                        # pop if it is still the head, else the overflow
                        # already accounted for it and the new head must
                        # not be silently discarded
                        if peer.outbox and peer.outbox[0] is entry:
                            peer.outbox.popleft()

                elif peer.dual_reconcile_needed:
                    # backlog drained after an overflow drop: bounce DUAL
                    # state with this (live) peer so whatever the dropped
                    # message carried is regenerated from a clean slate.
                    # peer_down/peer_up enqueue fresh messages into this
                    # same outbox; the loop delivers them next.
                    peer.dual_reconcile_needed = False
                    self._bump("kvstore.dual.num_overflow_reconcile")
                    self.dual.peer_down(peer.name)
                    self.dual.peer_up(peer.name, 1)
                    continue
                else:
                    return
                try:
                    await send_once()
                    done()
                    delay = DUAL_SEND_RETRY_INITIAL_S
                    failures = 0
                except Exception as exc:
                    self._bump(failure_counter)
                    failures += 1
                    if failures % 8 == 1:
                        log.warning(
                            "dual: send to %s failing (attempt %d): %r",
                            peer.name,
                            failures,
                            exc,
                        )
                    await asyncio.sleep(delay)
                    delay = min(delay * 2, DUAL_SEND_MAX_BACKOFF_S)

    def _dual_to_peer(self, peer: KvStorePeer, msgs) -> None:
        if len(peer.outbox) >= DUAL_SEND_BACKLOG_MAX:
            # drop oldest; a live peer is reconciled by the drainer once
            # the backlog clears (dual_reconcile_needed), a dead one by
            # the reconnect-time peer_down/peer_up
            peer.outbox.popleft()
            peer.dual_reconcile_needed = True
            self._bump("kvstore.dual.num_pkt_backlog_dropped")

        async def send_once():
            await self.store.transport.dual_messages(
                peer.spec, self.area, msgs
            )

        peer.outbox.append((send_once, "kvstore.dual.num_pkt_send_failure"))
        self.store._spawn(self._drain_peer_outbox(peer))

    def _process_nexthop_change(
        self, root_id: str, old_nh: Optional[str], new_nh: Optional[str]
    ) -> None:
        """SPT parent changed: (un)register as child remotely + full-sync
        with the new parent (reference: KvStoreDb::processNexthopChange,
        KvStore.cpp:2310-2363)."""
        log.info(
            "dual nexthop change: root-id (%s) %s -> %s",
            root_id,
            old_nh or "none",
            new_nh or "none",
        )
        if new_nh is not None:
            peer = self.peers.get(new_nh)
            if peer is not None:
                self._send_topo_set(
                    peer, FloodTopoSetParams(
                        root_id=root_id,
                        src_id=self.store.node_id,
                        set_child=True,
                    )
                )
                # full-sync with the new parent so the SPT edge is never a
                # disconnected state (reference enqueues peersToSyncWith_)
                if peer.spec.state != KvStorePeerState.IDLE:
                    peer.spec.state = KvStorePeerState.IDLE
                self._schedule_sync(0.0)
        if old_nh is not None:
            peer = self.peers.get(old_nh)
            if peer is not None:
                self._send_topo_set(
                    peer, FloodTopoSetParams(
                        root_id=root_id,
                        src_id=self.store.node_id,
                        set_child=False,
                    )
                )

    def _send_topo_set(self, peer: KvStorePeer, params) -> None:
        # coalesce by (root, all_roots): latest set wins — child add/remove
        # is idempotent, so only the final state needs delivering.
        # all_roots is normalized (it defaults to None) so the
        # already-pending guard in reassert_spt_children matches.
        key = (params.root_id, bool(params.all_roots))
        peer.pending_topo_set[key] = params
        self.store._spawn(self._drain_peer_outbox(peer))

    def reassert_spt_children(self) -> None:
        """Re-register as a child with every current SPT parent.

        Belt-and-braces on top of _send_reliably: if our parent restarted or
        otherwise lost its child table, re-assertion (idempotent set insert)
        re-attaches us.  No reference equivalent — its ZMQ channel was
        reliable and peers restarting re-ran the whole peer FSM."""
        if self._spt_reassert_timer is not None:
            self._spt_reassert_timer.cancel()
            self._spt_reassert_timer = None
        if not self.peers:
            return  # re-armed by the next add_peers
        for root_id, dual in self.dual.duals.items():
            nexthop = dual.info.nexthop
            if nexthop is None or nexthop == self.store.node_id:
                continue
            peer = self.peers.get(nexthop)
            if peer is None:
                continue
            if (root_id, False) in peer.pending_topo_set:
                continue  # a set for this root is already pending/retrying
            self._send_topo_set(
                peer,
                FloodTopoSetParams(
                    root_id=root_id,
                    src_id=self.store.node_id,
                    set_child=True,
                ),
            )
        self._spt_reassert_timer = self.store.schedule_timeout(
            SPT_REASSERT_INTERVAL_S, self.reassert_spt_children
        )

    def anti_entropy_sync(self) -> None:
        """Periodic digest full-sync with the SPT parent.

        Publications flooded by the parent while it (transiently) did not
        know us as a child are skipped silently — we are INITIALIZED, so
        they are not even captured in pending_flood_keys.  A low-frequency
        3-way sync (hashes only when already consistent) closes that
        residual loss window."""
        if self._anti_entropy_timer is not None:
            self._anti_entropy_timer.cancel()
            self._anti_entropy_timer = None
        if not self.peers:
            return  # re-armed by the next add_peers
        root_id = self.dual.get_spt_root_id()
        parent = (
            self.dual.get_dual(root_id).info.nexthop
            if root_id is not None
            else None
        )
        if parent is not None and parent != self.store.node_id:
            peer = self.peers.get(parent)
            if peer is not None and peer.spec.state == KvStorePeerState.INITIALIZED:
                # steady-state reconciliation, not an initial sync: flag it
                # so completion neither re-fires KvStoreSyncEvent (which
                # gates downstream initialization) nor inflates the
                # full-sync counters
                peer.anti_entropy_pending = True
                peer.spec.state = KvStorePeerState.IDLE
                self._bump("kvstore.num_anti_entropy_sync")
                self._schedule_sync(0.0)
        self._anti_entropy_timer = self.store.schedule_timeout(
            SPT_ANTI_ENTROPY_SYNC_S, self.anti_entropy_sync
        )

    def process_dual_messages(self, msgs) -> None:
        """Peer-facing entry (reference: KvStore.cpp:906-923 — which drops
        DUAL traffic when the optimization is off, as must we: a disabled
        node has an empty neighbor view and would wedge enabled queriers
        waiting on replies that never come)."""
        if not self.store.enable_flood_optimization:
            self._bump("kvstore.dual.num_pkt_dropped")
            return
        self._bump("kvstore.dual.num_pkt_recv")
        peer = self.peers.get(msgs.src_id)
        if peer is not None:
            peer.dual_seen = True
        self.dual.process_dual_messages(msgs)

    def process_flood_topo_set(self, params) -> None:
        """FLOOD_TOPO_SET (reference: KvStoreDb::processFloodTopoSet,
        KvStore.cpp:2231-2263)."""
        if not self.store.enable_flood_optimization:
            return
        peer = self.peers.get(params.src_id)
        if peer is not None:
            peer.dual_seen = True
        if params.all_roots and not params.set_child:
            for dual in self.dual.duals.values():
                dual.remove_child(params.src_id)
            return
        if not self.dual.has_dual(params.root_id):
            log.error("processFloodTopoSet unknown root-id %s", params.root_id)
            return
        dual = self.dual.get_dual(params.root_id)
        if params.set_child:
            dual.add_child(params.src_id)
        else:
            dual.remove_child(params.src_id)

    def process_flood_topo_get(self):
        """FLOOD_TOPO_GET (reference: KvStoreDb::processFloodTopoGet,
        KvStore.cpp:2195-2228)."""
        infos = SptInfos()
        for root_id, dual in self.dual.duals.items():
            info = dual.info
            infos.infos[root_id] = SptInfo(
                passive=info.sm.state == DualState.PASSIVE,
                cost=info.distance,
                parent=info.nexthop,
                children=sorted(dual.children()),
            )
        root_id = self.dual.get_spt_root_id()
        infos.flood_root_id = root_id
        infos.flood_peers = sorted(self._flood_peers(root_id))
        return infos

    # -- reads ---------------------------------------------------------------

    def get_key_vals(self, keys: Iterable[str]) -> Publication:
        pub = Publication(area=self.area)
        for key in keys:
            val = self.kv.get(key)
            if val is not None:
                pub.key_vals[key] = _copy_value(val)
        self.update_publication_ttl(pub)
        return pub

    def dump_all_with_filters(
        self,
        filters: KvStoreFilters,
        match_all: bool = False,
        do_not_publish_value: bool = False,
    ) -> Publication:
        """Reference: dumpAllWithFilters."""
        pub = Publication(area=self.area)
        match = filters.key_match_all if match_all else filters.key_match
        for key, val in self.kv.items():
            if not match(key, val):
                continue
            out = _copy_value(val)
            if do_not_publish_value:
                out.value = None
            pub.key_vals[key] = out
        return pub

    def dump_hash_with_filters(self, filters: KvStoreFilters) -> Publication:
        """Reference: dumpHashWithFilters — version/originator/hash/ttl only."""
        pub = Publication(area=self.area)
        for key, val in self.kv.items():
            if not filters.key_match(key, val):
                continue
            pub.key_vals[key] = Value(
                version=val.version,
                originator_id=val.originator_id,
                value=None,
                ttl_ms=val.ttl_ms,
                ttl_version=val.ttl_version,
                hash=val.hash,
            )
        return pub

    def dump_difference(
        self, my_key_vals: dict[str, Value], req_key_vals: dict[str, Value]
    ) -> Publication:
        """Reference: dumpDifference — keyVals I know better, plus
        tobe_updated_keys the requester knows better."""
        pub = Publication(area=self.area, tobe_updated_keys=[])
        for key in set(my_key_vals) | set(req_key_vals):
            mine = my_key_vals.get(key)
            theirs = req_key_vals.get(key)
            if mine is None:
                pub.tobe_updated_keys.append(key)
                continue
            if theirs is None:
                pub.key_vals[key] = mine
                continue
            rc = compare_values(mine, theirs)
            if rc in (1, -2):
                pub.key_vals[key] = mine
            if rc in (-1, -2):
                pub.tobe_updated_keys.append(key)
        return pub

    # -- transport-facing request handlers ------------------------------------

    def process_full_dump_request(self, params: KeyDumpParams) -> Publication:
        """Server side of full sync (reference: OpenrCtrlHandler
        semifuture_getKvStoreKeyValsFilteredArea -> KvStoreDb)."""
        filters = KvStoreFilters(params.keys, params.originator_ids)
        pub = self.dump_all_with_filters(filters)
        if params.key_val_hashes is not None:
            pub = self.dump_difference(pub.key_vals, params.key_val_hashes)
        self._bump("kvstore.cmd_key_dump")
        self.update_publication_ttl(pub)
        return pub

    def process_key_set_request(self, params: KeySetParams) -> None:
        """Server side of KEY_SET / flooding (reference:
        semifuture_setKvStoreKeyVals -> mergePublication)."""
        self._bump("kvstore.cmd_key_set")
        pub = Publication(
            key_vals=params.key_vals,
            node_ids=params.node_ids,
            flood_root_id=params.flood_root_id,
            area=self.area,
        )
        self.merge_publication(pub)

    # -- merge + flood --------------------------------------------------------

    def merge_publication(
        self, pub: Publication, sender_id: Optional[str] = None
    ) -> int:
        """Reference: mergePublication (KvStore.cpp)."""
        tr = _trace.TRACE
        if tr is not None:
            # trace-context birth: a publication entering this node; the
            # span rides the kvstore_updates queue into Decision and is
            # finished by the Fib terminal once routes are programmed
            root = tr.root("kvstore.publication", area=self.area)
            if root is not None:
                with tr.activate((root,)):
                    return self._merge_publication_impl(pub, sender_id)
        return self._merge_publication_impl(pub, sender_id)

    def _merge_publication_impl(
        self, pub: Publication, sender_id: Optional[str]
    ) -> int:
        self._bump("kvstore.received_publications")
        self._bump("kvstore.received_key_vals", len(pub.key_vals))

        need_finalize = (
            sender_id is not None
            and pub.tobe_updated_keys is not None
            and len(pub.tobe_updated_keys) > 0
        )
        if not pub.key_vals and not need_finalize:
            return 0
        # loop prevention
        if pub.node_ids is not None and self.store.node_id in pub.node_ids:
            self._bump("kvstore.looped_publications")
            return 0

        with _trace.maybe_child("kvstore.merge"):
            delta = Publication(
                key_vals=merge_key_values(
                    self.kv, pub.key_vals, self.store.filters
                ),
                flood_root_id=pub.flood_root_id,
                area=self.area,
                node_ids=(
                    list(pub.node_ids) if pub.node_ids is not None else None
                ),
            )
        kv_update_cnt = len(delta.key_vals)
        self._bump("kvstore.updated_key_vals", kv_update_cnt)
        self.update_ttl_countdown_queue(delta)
        if delta.key_vals:
            # sender_id matters when the publication has no node_ids trail
            # (full-sync responses): without it the delta would be captured
            # in the sender's pending_flood_keys and echoed straight back
            self.flood_publication(delta, sender_id=sender_id)
        if need_finalize:
            self.finalize_full_sync(pub.tobe_updated_keys, sender_id)
        return kv_update_cnt

    def set_key_vals(self, params: KeySetParams) -> None:
        """Local API origination (reference: setKvStoreKeyVals)."""
        for val in params.key_vals.values():
            if val.hash is None:
                val.hash = generate_hash(val.version, val.originator_id, val.value)
        self.process_key_set_request(params)

    def flood_publication(
        self,
        pub: Publication,
        rate_limit: bool = True,
        set_flood_root: bool = True,
        sender_id: Optional[str] = None,
    ) -> None:
        """Reference: floodPublication (KvStore.cpp).

        `sender_id` identifies the peer the publication was learned from
        when there is no node_ids trail (full-sync responses)."""
        # Locally-originated updates ride the SPT rooted at the current
        # flood root (reference: floodPublication stamps floodRootId when
        # the optimization is on, KvStore.cpp:2841-2864).  Stamped before
        # the rate-limit buffer so buffered publications keep their SPT
        # routing instead of falling back to full mesh on flush.
        if (
            set_flood_root
            and pub.flood_root_id is None
            and self.store.enable_flood_optimization
        ):
            pub.flood_root_id = self.dual.get_spt_root_id()

        if self._flood_limiter and rate_limit and not self._flood_limiter.consume(1):
            self._buffer_publication(pub, sender_id)
            if self._pending_flood_timer is None:
                self._pending_flood_timer = self.store.schedule_timeout(
                    FLOOD_PENDING_PUBLICATION_S, self._flood_buffered
                )
            return
        if self._publication_buffer:
            self._buffer_publication(pub, sender_id)
            self._flood_buffered_now()
            return

        self.update_publication_ttl(pub, remove_about_to_expire=True)
        if not pub.key_vals and not pub.expired_keys:
            return

        if pub.node_ids:
            sender_id = pub.node_ids[-1]
        if pub.node_ids is None:
            pub.node_ids = []
        pub.node_ids.append(self.store.node_id)

        # internal subscribers
        tr = _trace.TRACE
        if tr is not None and not tr.scope():
            # locally-originated publication (API origination, TTL
            # expiry): the trace is born at the flood chokepoint instead
            # of merge_publication
            root = tr.root("kvstore.publication", area=self.area)
            if root is not None:
                with tr.activate((root,)):
                    self.store.kvstore_updates_queue.push(pub)
            else:
                self.store.kvstore_updates_queue.push(pub)
        else:
            self.store.kvstore_updates_queue.push(pub)
        self._bump("kvstore.num_updates")

        if not pub.key_vals:
            return  # expired-keys-only publications stay local

        params = KeySetParams(
            key_vals=dict(pub.key_vals),
            node_ids=list(pub.node_ids),
            flood_root_id=pub.flood_root_id,
            timestamp_ms=int(time.time() * 1000),
        )
        for peer_name in self._flood_peers(pub.flood_root_id):
            peer = self.peers.get(peer_name)
            if peer is None or peer_name == sender_id:
                continue
            if peer.spec.state != KvStorePeerState.INITIALIZED:
                peer.pending_flood_keys.update(pub.key_vals)
                continue
            self._bump("kvstore.thrift.num_flood_pub")
            self.store._spawn(self._flood_to_peer(peer, params))

    async def _flood_to_peer(self, peer: KvStorePeer, params: KeySetParams) -> None:
        try:
            await self.store.transport.key_set(peer.spec, self.area, params)
        except Exception:
            self.process_sync_failure(peer.name)
            self._bump("kvstore.thrift.num_flood_pub_failure")

    def _flood_peers(self, flood_root_id: Optional[str]) -> list[str]:
        """SPT-constrained flood peers, falling back to full mesh when the
        optimization is off or no valid SPT exists (reference:
        KvStoreDb::getFloodPeers, KvStore.cpp:2813-2834)."""
        spt_peers = self.dual.get_spt_peers(flood_root_id)
        flood_to_all = (
            not self.store.enable_flood_optimization or not spt_peers
        )
        # peers that have never spoken DUAL (flood-opt-disabled nodes in a
        # mixed-config mesh) always get the full flood
        return [
            name
            for name, peer in self.peers.items()
            if flood_to_all or name in spt_peers or not peer.dual_seen
        ]

    def _buffer_publication(
        self, pub: Publication, sender_id: Optional[str] = None
    ) -> None:
        self._bump("kvstore.rate_limit_suppress")
        # keyed by (flood-root, learned-from) so the flush preserves both the
        # SPT routing and the sender-echo exclusion (the node_ids trail also
        # ends with the sender when present)
        if pub.node_ids:
            sender_id = pub.node_ids[-1]
        buf = self._publication_buffer.setdefault(
            (pub.flood_root_id, sender_id), set()
        )
        buf.update(pub.key_vals)
        buf.update(pub.expired_keys)

    def _flood_buffered(self) -> None:
        self._pending_flood_timer = None
        self._flood_buffered_now()

    def _flood_buffered_now(self) -> None:
        """Reference: floodBufferedUpdates."""
        if not self._publication_buffer:
            return
        buffers, self._publication_buffer = self._publication_buffer, {}
        for (flood_root_id, sender_id), keys in buffers.items():
            pub = Publication(area=self.area, flood_root_id=flood_root_id)
            for key in keys:
                val = self.kv.get(key)
                if val is not None:
                    pub.key_vals[key] = _copy_value(val)
                else:
                    pub.expired_keys.append(key)
            self.flood_publication(
                pub,
                rate_limit=False,
                set_flood_root=False,
                sender_id=sender_id,
            )

    # -- full sync ------------------------------------------------------------

    def add_peers(self, peers: dict[str, PeerSpec]) -> None:
        """Reference: addThriftPeers (KvStore.cpp:1660+)."""
        new_names: list[str] = []
        for name, new_spec in peers.items():
            spec = PeerSpec(
                peer_addr=new_spec.peer_addr,
                ctrl_port=new_spec.ctrl_port,
                state=KvStorePeerState.IDLE,
            )
            existing = self.peers.get(name)
            if existing is not None:
                existing.spec = spec
                # a re-added peer's next sync is a genuine initial sync
                existing.anti_entropy_pending = False
            else:
                self.peers[name] = KvStorePeer(
                    name=name,
                    spec=spec,
                    backoff=ExponentialBackoff(INITIAL_BACKOFF_S, MAX_BACKOFF_S),
                )
                new_names.append(name)
        # DUAL: every KvStore peering link has unit cost (reference:
        # KvStore.cpp addPeers -> DualNode::peerUp(peerName, 1)).  A new
        # peer may have stale child registrations for us from a
        # non-graceful restart: clear them all first (reference:
        # unsetChildAll, KvStore.cpp:1796-1800).
        if self.store.enable_flood_optimization:
            for name in new_names:
                peer = self.peers[name]
                self._send_topo_set(
                    peer,
                    FloodTopoSetParams(
                        root_id="",
                        src_id=self.store.node_id,
                        set_child=False,
                        all_roots=True,
                    ),
                )
                self.dual.peer_up(name, 1)
            if self._spt_reassert_timer is None:
                self._spt_reassert_timer = self.store.schedule_timeout(
                    SPT_REASSERT_INTERVAL_S, self.reassert_spt_children
                )
            if self._anti_entropy_timer is None:
                self._anti_entropy_timer = self.store.schedule_timeout(
                    SPT_ANTI_ENTROPY_SYNC_S, self.anti_entropy_sync
                )
        self._schedule_sync(0.0)

    def del_peers(self, peers: Iterable[str]) -> None:
        for name in peers:
            existed = self.peers.pop(name, None)
            if existed is not None and self.store.enable_flood_optimization:
                self.dual.peer_down(name)

    def dump_peers(self) -> dict[str, PeerSpec]:
        return {name: peer.spec for name, peer in self.peers.items()}

    def get_peer_state(self, peer_name: str) -> Optional[KvStorePeerState]:
        peer = self.peers.get(peer_name)
        return peer.spec.state if peer else None

    def get_peers_by_state(self, state: KvStorePeerState) -> list[str]:
        return [n for n, p in self.peers.items() if p.spec.state == state]

    def _schedule_sync(self, delay_s: float) -> None:
        if self._sync_timer is not None:
            self._sync_timer.cancel()
        self._sync_timer = self.store.schedule_timeout(
            delay_s, self.request_peer_sync
        )

    def request_peer_sync(self) -> None:
        """Promote IDLE peers to SYNCING and fire full-dump requests
        (reference: requestThriftPeerSync, KvStore.cpp:1380)."""
        self._sync_timer = None
        timeout = MAX_BACKOFF_S
        num_syncing = len(self.get_peers_by_state(KvStorePeerState.SYNCING))
        for name, peer in self.peers.items():
            if peer.spec.state != KvStorePeerState.IDLE:
                continue
            if not peer.backoff.can_try_now():
                timeout = min(timeout, peer.backoff.get_time_remaining_until_retry())
                continue
            peer.spec.state = get_next_state(
                peer.spec.state, KvStorePeerEvent.PEER_ADD
            )
            num_syncing += 1
            params = KeyDumpParams()
            if self.store.filters is not None:
                params.keys = list(self.store.filters.key_prefixes)
                params.originator_ids = list(self.store.filters.originator_ids)
            params.key_val_hashes = self.dump_hash_with_filters(
                KvStoreFilters()
            ).key_vals
            self._bump("kvstore.thrift.num_full_sync")
            self.store._spawn(self._full_sync_with_peer(peer, params))
            if num_syncing > self._parallel_sync_limit:
                timeout = MAX_BACKOFF_S
                break
        if (
            self.get_peers_by_state(KvStorePeerState.IDLE)
            or num_syncing > self._parallel_sync_limit
        ):
            self._schedule_sync(timeout)

    async def _full_sync_with_peer(
        self, peer: KvStorePeer, params: KeyDumpParams
    ) -> None:
        try:
            pub = await self.store.transport.full_dump(
                peer.spec, self.area, params
            )
        except Exception:
            self._bump("kvstore.thrift.num_full_sync_failure")
            self.process_sync_failure(peer.name)
            return
        self.process_sync_success(peer.name, pub)

    def process_sync_success(self, peer_name: str, pub: Publication) -> None:
        """Reference: processThriftSuccess (KvStore.cpp:1530-1610)."""
        peer = self.peers.get(peer_name)
        if peer is None:
            return
        if peer.spec.state == KvStorePeerState.IDLE:
            return  # stale response; a new sync round will supersede it
        self.merge_publication(pub, sender_id=peer_name)
        peer.spec.state = get_next_state(
            peer.spec.state, KvStorePeerEvent.SYNC_RESP_RCVD
        )
        peer.backoff.report_success()
        if peer.anti_entropy_pending:
            # periodic reconciliation: don't re-fire initialization
            # signaling or the initial-sync counters in steady state
            peer.anti_entropy_pending = False
            self._bump("kvstore.num_anti_entropy_sync_success")
        else:
            self._bump("kvstore.thrift.num_full_sync_success")
            self.store.kvstore_sync_events_queue.push(
                KvStoreSyncEvent(peer_name, self.area)
            )
        self._parallel_sync_limit = min(
            2 * self._parallel_sync_limit, PARALLEL_SYNC_LIMIT_MAX
        )
        # deliver keys flooded while the peer was syncing (see
        # KvStorePeer.pending_flood_keys)
        if peer.pending_flood_keys:
            pending, peer.pending_flood_keys = peer.pending_flood_keys, set()
            self._flood_keys_to_peer(
                peer, pending, counter="kvstore.thrift.num_flood_pub"
            )
        if self.get_peers_by_state(KvStorePeerState.IDLE):
            self._schedule_sync(0.0)

    def _flood_keys_to_peer(
        self, peer: KvStorePeer, keys: Iterable[str], counter: str
    ) -> None:
        """Send the current values of `keys` directly to one peer (used by
        finalize_full_sync and the pending-flood flush)."""
        updates = Publication(area=self.area)
        for key in keys:
            val = self.kv.get(key)
            if val is not None:
                updates.key_vals[key] = _copy_value(val)
        self.update_publication_ttl(updates)
        if not updates.key_vals:
            return
        self._bump(counter)
        self.store._spawn(
            self._flood_to_peer(
                peer,
                KeySetParams(
                    key_vals=updates.key_vals,
                    node_ids=[self.store.node_id],
                    timestamp_ms=int(time.time() * 1000),
                ),
            )
        )

    def process_sync_failure(self, peer_name: str) -> None:
        """Reference: processThriftFailure (KvStore.cpp:1612-1650)."""
        peer = self.peers.get(peer_name)
        if peer is None:
            return
        self._bump("kvstore.full_sync_retries")
        peer.backoff.report_error()
        peer.spec.state = get_next_state(
            peer.spec.state, KvStorePeerEvent.THRIFT_API_ERROR
        )
        if self._sync_timer is None:
            self._schedule_sync(0.0)

    def finalize_full_sync(self, keys: list[str], sender_id: str) -> None:
        """Reference: finalizeFullSync — send back values the peer needs."""
        peer = self.peers.get(sender_id)
        if peer is None or peer.spec.state == KvStorePeerState.IDLE:
            return
        self._flood_keys_to_peer(
            peer, keys, counter="kvstore.thrift.num_finalized_sync"
        )

    # -- TTL ------------------------------------------------------------------

    def update_ttl_countdown_queue(self, pub: Publication) -> None:
        """Reference: updateTtlCountdownQueue."""
        now = time.monotonic()
        for key, value in pub.key_vals.items():
            if value.ttl_ms == TTL_INFINITY:
                continue
            entry = TtlCountdownEntry(
                expiry_time=now + value.ttl_ms / 1000.0,
                key=key,
                version=value.version,
                ttl_version=value.ttl_version,
                originator_id=value.originator_id,
            )
            if not self._ttl_heap or entry.expiry_time <= self._ttl_heap[0].expiry_time:
                self._schedule_ttl_cleanup(value.ttl_ms / 1000.0)
            heapq.heappush(self._ttl_heap, entry)

    def _schedule_ttl_cleanup(self, delay_s: float) -> None:
        if self._ttl_timer is not None:
            self._ttl_timer.cancel()
        self._ttl_timer = self.store.schedule_timeout(
            max(0.0, delay_s), self.cleanup_ttl_countdown_queue
        )

    def cleanup_ttl_countdown_queue(self) -> None:
        """Reference: cleanupTtlCountdownQueue."""
        self._ttl_timer = None
        expired: list[str] = []
        now = time.monotonic()
        while self._ttl_heap and self._ttl_heap[0].expiry_time <= now:
            top = heapq.heappop(self._ttl_heap)
            val = self.kv.get(top.key)
            if (
                val is not None
                and val.version == top.version
                and val.originator_id == top.originator_id
                and val.ttl_version == top.ttl_version
            ):
                expired.append(top.key)
                del self.kv[top.key]
        if self._ttl_heap:
            self._schedule_ttl_cleanup(self._ttl_heap[0].expiry_time - now)
        if not expired:
            return
        self._bump("kvstore.expired_key_vals", len(expired))
        # expired keys are published to local subscribers only
        self.flood_publication(
            Publication(expired_keys=expired, area=self.area)
        )

    def update_publication_ttl(
        self, pub: Publication, remove_about_to_expire: bool = False
    ) -> None:
        """Set remaining TTL minus the decrement on outgoing values
        (reference: updatePublicationTtl)."""
        now = time.monotonic()
        by_key: dict[tuple, TtlCountdownEntry] = {}
        for entry in self._ttl_heap:
            by_key[
                (entry.key, entry.version, entry.originator_id, entry.ttl_version)
            ] = entry
        for key in list(pub.key_vals):
            val = pub.key_vals[key]
            entry = by_key.get((key, val.version, val.originator_id, val.ttl_version))
            if entry is None:
                continue
            time_left_ms = (entry.expiry_time - now) * 1000.0
            if time_left_ms <= self.store.ttl_decr_ms:
                del pub.key_vals[key]
                continue
            if remove_about_to_expire and time_left_ms < TTL_THRESHOLD_S * 1000.0:
                del pub.key_vals[key]
                continue
            val.ttl_ms = int(time_left_ms - self.store.ttl_decr_ms)


def _copy_value(val: Value) -> Value:
    return Value(
        version=val.version,
        originator_id=val.originator_id,
        value=val.value,
        ttl_ms=val.ttl_ms,
        ttl_version=val.ttl_version,
        hash=val.hash,
    )


# ---------------------------------------------------------------------------
# KvStore event base
# ---------------------------------------------------------------------------


class KvStore(OpenrEventBase):
    """Multi-area KvStore module (reference: KvStore, KvStore.h:541)."""

    def __init__(
        self,
        node_id: str,
        kvstore_updates_queue: ReplicateQueue[Publication],
        kvstore_sync_events_queue: ReplicateQueue[KvStoreSyncEvent],
        peer_updates_queue: Optional[RQueue[PeerEvent]] = None,
        *,
        transport: Optional[Any] = None,
        areas: Iterable[str] = ("0",),
        filters: Optional[KvStoreFilters] = None,
        flood_rate: Optional[tuple[float, float]] = None,  # (msgs/s, burst)
        ttl_decr_ms: int = 1,
        enable_flood_optimization: bool = False,
        is_flood_root: bool = True,
    ) -> None:
        super().__init__(name=f"kvstore-{node_id}")
        self.node_id = node_id
        self.kvstore_updates_queue = kvstore_updates_queue
        self.kvstore_sync_events_queue = kvstore_sync_events_queue
        self._peer_updates_queue = peer_updates_queue
        self.transport = transport
        self.filters = filters
        self.flood_rate = flood_rate
        self.ttl_decr_ms = ttl_decr_ms
        # DUAL flood-topology knobs (reference: enable_flood_optimization /
        # is_flood_root in KvStoreConfig, OpenrConfig.thrift:25)
        self.enable_flood_optimization = enable_flood_optimization
        self.is_flood_root = is_flood_root
        self._dbs: dict[str, KvStoreDb] = {
            area: KvStoreDb(self, area) for area in areas
        }

    def _db(self, area: str) -> KvStoreDb:
        db = self._dbs.get(area)
        if db is None:
            raise KeyError(f"unknown area {area!r}")
        return db

    @property
    def areas(self) -> list[str]:
        return list(self._dbs)

    def _spawn(self, coro) -> None:
        """Launch a transport coroutine from evb-thread context."""
        self._track(self._loop.create_task(coro))

    def run(self) -> None:
        super().run()
        self.wait_until_running()
        if self._peer_updates_queue is not None:
            self.run_in_event_base_thread(
                lambda: self.add_fiber_task(
                    self._peer_updates_fiber(), name="peerUpdates"
                )
            ).result()

    async def _peer_updates_fiber(self) -> None:
        while True:
            try:
                event = await self._peer_updates_queue.aget()
            except QueueClosedError:
                return
            db = self._dbs.get(event.area)
            if db is None:
                continue
            if event.peers_to_add:
                db.add_peers(event.peers_to_add)
            if event.peers_to_del:
                db.del_peers(event.peers_to_del)

    # -- thread-safe public API (reference: KvStore.h:541-683) ---------------

    def _call(self, fn):
        return self.run_in_event_base_thread(fn).result()

    def get_key_vals(self, area: str, keys: Iterable[str]) -> Publication:
        return self._call(lambda: self._db(area).get_key_vals(keys))

    def set_key_vals(
        self,
        area: str,
        key_vals: dict[str, Value],
        node_ids: Optional[list[str]] = None,
        flood_root_id: Optional[str] = None,
    ) -> None:
        params = KeySetParams(
            key_vals=key_vals, node_ids=node_ids, flood_root_id=flood_root_id
        )
        self._call(lambda: self._db(area).set_key_vals(params))

    def dump_all(
        self,
        area: str,
        key_prefixes: Iterable[str] = (),
        originator_ids: Iterable[str] = (),
        match_all: bool = False,
        do_not_publish_value: bool = False,
    ) -> Publication:
        filters = KvStoreFilters(key_prefixes, originator_ids)
        return self._call(
            lambda: self._db(area).dump_all_with_filters(
                filters, match_all, do_not_publish_value
            )
        )

    def dump_hashes(
        self,
        area: str,
        key_prefixes: Iterable[str] = (),
        originator_ids: Iterable[str] = (),
    ) -> Publication:
        filters = KvStoreFilters(key_prefixes, originator_ids)
        return self._call(lambda: self._db(area).dump_hash_with_filters(filters))

    def process_full_dump(self, area: str, params: KeyDumpParams) -> Publication:
        """Serve a peer/ctrl full-dump request (incl. 3-way diff + TTL
        adjustment) — the same path the in-process transport uses."""
        return self._call(
            lambda: self._db(area).process_full_dump_request(params)
        )

    def add_peers(self, area: str, peers: dict[str, PeerSpec]) -> None:
        self._call(lambda: self._db(area).add_peers(peers))

    def del_peers(self, area: str, peers: list[str]) -> None:
        self._call(lambda: self._db(area).del_peers(peers))

    def dump_peers(self, area: str) -> dict[str, PeerSpec]:
        return self._call(lambda: self._db(area).dump_peers())

    def get_peer_state(
        self, area: str, peer_name: str
    ) -> Optional[KvStorePeerState]:
        return self._call(lambda: self._db(area).get_peer_state(peer_name))

    # -- DUAL flood-topology API (reference: KvStore.h:268-272) --------------

    def process_dual_messages(self, area: str, msgs) -> None:
        self._call(lambda: self._db(area).process_dual_messages(msgs))

    def process_flood_topo_set(self, area: str, params) -> None:
        self._call(lambda: self._db(area).process_flood_topo_set(params))

    def get_flood_topo(self, area: str):
        return self._call(lambda: self._db(area).process_flood_topo_get())

    def get_counters(self) -> dict[str, int]:
        def _sum() -> dict[str, int]:
            out: dict[str, int] = {}
            for db in self._dbs.values():
                for k, v in db.counters.items():
                    out[k] = out.get(k, 0) + v
                out[f"kvstore.num_keys.{db.area}"] = len(db.kv)
            return out

        return self._call(_sum)
