"""KvStoreClientInternal: in-process client with key persistence.

Functional equivalent of the reference's KvStoreClientInternal
(openr/kvstore/KvStoreClientInternal.h:75-220):

- `persist_key`: advertise a key and keep re-advertising it — if another
  originator overwrites it (or the value differs), re-advertise with a
  bumped version so this node stays the owner;
- TTL refresh: for finite-TTL persisted keys, periodically bump ttlVersion
  so the key never expires while we own it;
- `check_persisted_keys`: periodic scan verifying persisted keys are still
  in the store (re-advertise if lost — e.g. store restarted);
- key subscriptions with exact-key and regex filters.

Runs on a caller-provided OpenrEventBase (the owning module's thread), and
watches the KvStore publications queue for overwrites.
"""

from __future__ import annotations

import re
from typing import Callable, Optional

from ..runtime.eventbase import OpenrEventBase
from ..runtime.queue import QueueClosedError, RQueue
from ..types import Publication, TTL_INFINITY, Value
from .kvstore import KvStore

# reference: Constants::kPersistKeyTimer
CHECK_PERSIST_INTERVAL_S = 60.0

KeyCallback = Callable[[str, Optional[Value]], None]


class KvStoreClientInternal:
    def __init__(
        self,
        evb: OpenrEventBase,
        node_id: str,
        kvstore: KvStore,
        kvstore_updates: Optional[RQueue[Publication]] = None,
        check_persist_interval_s: float = CHECK_PERSIST_INTERVAL_S,
    ) -> None:
        self.evb = evb
        self.node_id = node_id
        self.kvstore = kvstore
        # area -> key -> value we insist on
        self._persisted: dict[str, dict[str, Value]] = {}
        # (area, key) -> callback
        self._key_callbacks: dict[tuple[str, str], KeyCallback] = {}
        self._filter_callbacks: list[tuple[re.Pattern, KeyCallback]] = []
        self._ttl_timers: dict[tuple[str, str], object] = {}
        self._check_interval = check_persist_interval_s
        self._check_timer = None
        if kvstore_updates is not None:
            evb.run_in_event_base_thread(
                lambda: evb.add_fiber_task(
                    self._updates_fiber(kvstore_updates), name="kvClientUpdates"
                )
            ).result()
        self._schedule_check()

    def stop(self) -> None:
        for timer in self._ttl_timers.values():
            timer.cancel()
        self._ttl_timers.clear()
        if self._check_timer is not None:
            self._check_timer.cancel()
            self._check_timer = None

    # -- write API ------------------------------------------------------------

    def persist_key(
        self, area: str, key: str, value: bytes, ttl_ms: int = TTL_INFINITY
    ) -> None:
        """Reference: persistKey (KvStoreClientInternal.h:75)."""
        existing = self.kvstore.get_key_vals(area, [key]).key_vals.get(key)
        version = 1
        if existing is not None:
            if existing.originator_id == self.node_id and existing.value == value:
                version = existing.version  # already ours and identical
            else:
                version = existing.version + 1
        val = Value(
            version=version,
            originator_id=self.node_id,
            value=value,
            ttl_ms=ttl_ms,
            ttl_version=0,
        )
        self._persisted.setdefault(area, {})[key] = val
        self.kvstore.set_key_vals(area, {key: _fresh(val)})
        self._schedule_ttl_refresh(area, key)

    def set_key(
        self,
        area: str,
        key: str,
        value: bytes,
        version: Optional[int] = None,
        ttl_ms: int = TTL_INFINITY,
    ) -> Value:
        """One-shot advertise (reference: setKey,
        KvStoreClientInternal.h:90)."""
        if version is None:
            existing = self.kvstore.get_key_vals(area, [key]).key_vals.get(key)
            version = (existing.version + 1) if existing is not None else 1
        val = Value(
            version=version,
            originator_id=self.node_id,
            value=value,
            ttl_ms=ttl_ms,
        )
        self.kvstore.set_key_vals(area, {key: _fresh(val)})
        return val

    def unset_key(self, area: str, key: str) -> None:
        """Stop persisting; the key stays in the store until TTL expiry
        (reference: unsetKey, KvStoreClientInternal.h:103)."""
        self._persisted.get(area, {}).pop(key, None)
        timer = self._ttl_timers.pop((area, key), None)
        if timer is not None:
            timer.cancel()

    def clear_key(
        self, area: str, key: str, new_value: bytes, ttl_ms: int
    ) -> None:
        """Overwrite with a short-TTL tombstone value (reference: clearKey)."""
        self.unset_key(area, key)
        existing = self.kvstore.get_key_vals(area, [key]).key_vals.get(key)
        if existing is None:
            return
        self.kvstore.set_key_vals(
            area,
            {
                key: Value(
                    version=existing.version + 1,
                    originator_id=self.node_id,
                    value=new_value,
                    ttl_ms=ttl_ms,
                )
            },
        )

    # -- read / subscribe API --------------------------------------------------

    def get_key(self, area: str, key: str) -> Optional[Value]:
        return self.kvstore.get_key_vals(area, [key]).key_vals.get(key)

    def dump_all_with_prefix(self, area: str, prefix: str = "") -> dict[str, Value]:
        return self.kvstore.dump_all(area, key_prefixes=[prefix] if prefix else []).key_vals

    def subscribe_key(
        self, area: str, key: str, callback: KeyCallback
    ) -> Optional[Value]:
        """Reference: subscribeKey (KvStoreClientInternal.h:134).  Returns
        current value if any."""
        self._key_callbacks[(area, key)] = callback
        return self.get_key(area, key)

    def unsubscribe_key(self, area: str, key: str) -> None:
        self._key_callbacks.pop((area, key), None)

    def subscribe_key_filter(self, regex: str, callback: KeyCallback) -> None:
        self._filter_callbacks.append((re.compile(regex), callback))

    def unsubscribe_key_filter(self) -> None:
        self._filter_callbacks.clear()

    # -- internals -------------------------------------------------------------

    async def _updates_fiber(self, reader: RQueue[Publication]) -> None:
        while True:
            try:
                pub = await reader.aget()
            except QueueClosedError:
                return
            self._process_publication(pub)

    def _process_publication(self, pub: Publication) -> None:
        persisted = self._persisted.get(pub.area, {})
        for key, value in pub.key_vals.items():
            # subscriptions
            cb = self._key_callbacks.get((pub.area, key))
            if cb is not None:
                cb(key, value)
            for pattern, fcb in self._filter_callbacks:
                if pattern.search(key):
                    fcb(key, value)
            # ownership enforcement (reference: processPublicationForKey)
            mine = persisted.get(key)
            if mine is None or value.value is None:
                continue
            if value.originator_id != self.node_id or value.value != mine.value:
                mine.version = value.version + 1
                mine.ttl_version = 0
                self.kvstore.set_key_vals(pub.area, {key: _fresh(mine)})
        for key in pub.expired_keys:
            cb = self._key_callbacks.get((pub.area, key))
            if cb is not None:
                cb(key, None)
            mine = persisted.get(key)
            if mine is not None:
                # our key expired (e.g. store restarted): re-advertise
                self.kvstore.set_key_vals(pub.area, {key: _fresh(mine)})

    def _schedule_ttl_refresh(self, area: str, key: str) -> None:
        """Bump ttlVersion at ttl/4 cadence (reference: ttl refresh in
        advertisePendingKeys / scheduleTtlUpdates)."""
        val = self._persisted.get(area, {}).get(key)
        if val is None or val.ttl_ms == TTL_INFINITY:
            return
        existing = self._ttl_timers.pop((area, key), None)
        if existing is not None:
            existing.cancel()

        def _refresh() -> None:
            mine = self._persisted.get(area, {}).get(key)
            if mine is None:
                return
            mine.ttl_version += 1
            # TTL-refresh advertisement: version-only (value=None)
            self.kvstore.set_key_vals(
                area,
                {
                    key: Value(
                        version=mine.version,
                        originator_id=self.node_id,
                        value=None,
                        ttl_ms=mine.ttl_ms,
                        ttl_version=mine.ttl_version,
                    )
                },
            )
            self._schedule_ttl_refresh(area, key)

        self._ttl_timers[(area, key)] = self.evb.schedule_timeout(
            val.ttl_ms / 4000.0, _refresh
        )

    def _schedule_check(self) -> None:
        self._check_timer = self.evb.schedule_timeout(
            self._check_interval, self._check_persisted_keys
        )

    def _check_persisted_keys(self) -> None:
        """Reference: checkPersistKeyInStore (KvStoreClientInternal.h:220)."""
        for area, keys in self._persisted.items():
            missing = {
                key: _fresh(val)
                for key, val in keys.items()
                if self.kvstore.get_key_vals(area, [key]).key_vals.get(key) is None
            }
            if missing:
                self.kvstore.set_key_vals(area, missing)
        self._schedule_check()


def _fresh(val: Value) -> Value:
    return Value(
        version=val.version,
        originator_id=val.originator_id,
        value=val.value,
        ttl_ms=val.ttl_ms,
        ttl_version=val.ttl_version,
    )
