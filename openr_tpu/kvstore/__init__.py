"""Replicated CRDT key-value store (the distributed state backbone).

Functional equivalent of the reference's KvStore (openr/kvstore/): per-area
eventually-consistent replicated store with (version, originatorId, value,
ttlVersion) conflict resolution, TTL eviction, 3-way full sync, incremental
flooding, and a peer FSM (IDLE -> SYNCING -> INITIALIZED).
"""

from .kvstore import (
    InProcessTransport,
    KvStore,
    KvStoreFilters,
    compare_values,
    generate_hash,
    merge_key_values,
)
from .client import KvStoreClientInternal

__all__ = [
    "InProcessTransport",
    "KvStore",
    "KvStoreClientInternal",
    "KvStoreFilters",
    "compare_values",
    "generate_hash",
    "merge_key_values",
]
