"""DUAL (Diffusing Update Algorithm) — per-root flooding spanning trees.

Functional equivalent of the reference's `openr/dual/Dual.{h,cpp}`: every
node runs one `Dual` instance per discovered root computing its shortest
route to that root via EIGRP-style diffusing computations (the SNC feasible
condition, ACTIVE0-3/PASSIVE state machine, query/reply diffusion).  The
union of (nexthop -> parent) choices forms a spanning tree per root; KvStore
floods along the tree of the smallest passive root instead of full-mesh
(`KvStoreDb.get_flood_peers`).

Algorithm background: J.J. Garcia-Lunes-Aceves, "Loop-Free Routing Using
Diffusing Computations" (the paper the reference cites at Dual.h:29).

Mapping to the reference:
- `DualStateMachine.process_event`  <- Dual.cpp:12-60
- `Dual.peer_up/peer_down/peer_cost_change` <- Dual.cpp:401-527
- `Dual.process_update/query/reply` <- Dual.cpp:529-715
- feasible condition (SNC)          <- Dual.cpp:148-169 meetFeasibleCondition
- `DualNode`                        <- Dual.cpp:717-971

All distances are int; `INFINITY64` stands for thrift INT64_MAX.
"""

from __future__ import annotations

import enum
import logging
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..types import DualMessage, DualMessages, DualMessageType

log = logging.getLogger(__name__)

INFINITY64 = (1 << 63) - 1  # thrift int64 max == "no route"


class DualState(enum.Enum):
    """PASSIVE: converged, usable.  ACTIVE0-3: diffusing computation in
    progress (reference: Dual.h:31-37)."""

    ACTIVE0 = 0
    ACTIVE1 = 1
    ACTIVE2 = 2
    ACTIVE3 = 3
    PASSIVE = 4


class DualEvent(enum.Enum):
    """Reference: Dual.h:42-47."""

    QUERY_FROM_SUCCESSOR = 0
    LAST_REPLY = 1
    INCREASE_D = 2
    OTHERS = 3


class DualStateMachine:
    """Reference: DualStateMachine::processEvent (Dual.cpp:12-60)."""

    def __init__(self) -> None:
        self.state = DualState.PASSIVE

    def process_event(self, event: DualEvent, fc: bool = True) -> None:
        s, e = self.state, event
        if s == DualState.PASSIVE:
            if fc:
                return
            self.state = (
                DualState.ACTIVE3
                if e == DualEvent.QUERY_FROM_SUCCESSOR
                else DualState.ACTIVE1
            )
        elif s == DualState.ACTIVE0:
            if e != DualEvent.LAST_REPLY:
                return
            self.state = DualState.PASSIVE if fc else DualState.ACTIVE2
        elif s == DualState.ACTIVE1:
            if e == DualEvent.INCREASE_D:
                self.state = DualState.ACTIVE0
            elif e == DualEvent.LAST_REPLY:
                self.state = DualState.PASSIVE
            elif e == DualEvent.QUERY_FROM_SUCCESSOR:
                self.state = DualState.ACTIVE2
        elif s == DualState.ACTIVE2:
            if e != DualEvent.LAST_REPLY:
                return
            self.state = DualState.PASSIVE if fc else DualState.ACTIVE3
        elif s == DualState.ACTIVE3:
            if e == DualEvent.LAST_REPLY:
                self.state = DualState.PASSIVE
            elif e == DualEvent.INCREASE_D:
                self.state = DualState.ACTIVE2


@dataclass(slots=True)
class NeighborInfo:
    """Reference: Dual::NeighborInfo (Dual.h:127-134)."""

    report_distance: int = INFINITY64
    expect_reply: bool = False
    need_to_reply: bool = False


@dataclass(slots=True)
class DualPerRootCounters:
    """Reference: thrift::DualPerRootCounters."""

    query_sent: int = 0
    query_recv: int = 0
    reply_sent: int = 0
    reply_recv: int = 0
    update_sent: int = 0
    update_recv: int = 0
    total_sent: int = 0
    total_recv: int = 0


@dataclass(slots=True)
class RouteInfo:
    """Reference: Dual::RouteInfo (Dual.h:137-195)."""

    distance: int = INFINITY64
    report_distance: int = INFINITY64
    feasible_distance: int = INFINITY64
    nexthop: Optional[str] = None
    sm: DualStateMachine = field(default_factory=DualStateMachine)
    neighbor_infos: dict[str, NeighborInfo] = field(default_factory=dict)
    cornet: list[str] = field(default_factory=list)  # stack of queriers

    def __str__(self) -> str:
        return (
            f"[{self.sm.state.name}] {self.nexthop or 'None'} "
            f"({self.distance}, {self.report_distance}, "
            f"{self.feasible_distance})"
        )


MsgsToSend = dict[str, DualMessages]
NexthopCb = Callable[[Optional[str], Optional[str]], None]


def add_distances(d1: int, d2: int) -> int:
    """Saturating add (reference: Dual::addDistances, Dual.cpp:392-399)."""
    if d1 == INFINITY64 or d2 == INFINITY64:
        return INFINITY64
    return d1 + d2


class Dual:
    """Per-(node, root) DUAL computation (reference: class Dual,
    Dual.h:67-294)."""

    def __init__(
        self,
        node_id: str,
        root_id: str,
        local_distances: dict[str, int],
        nexthop_cb: Optional[NexthopCb] = None,
    ) -> None:
        self.node_id = node_id
        self.root_id = root_id
        # SHARED with DualNode (reference passes by ref — peerUp on the
        # node updates all duals' view through per-dual copies; we copy
        # like the reference's constructor and update in peer events)
        self.local_distances: dict[str, int] = dict(local_distances)
        self.nexthop_cb = nexthop_cb
        self.info = RouteInfo()
        self.counters: dict[str, DualPerRootCounters] = {}
        self._children: set[str] = set()
        if root_id == node_id:
            self.info.distance = 0
            self.info.report_distance = 0
            self.info.feasible_distance = 0
            self.info.nexthop = node_id

    # -- counters ------------------------------------------------------------

    def _cnt(self, neighbor: str) -> DualPerRootCounters:
        return self.counters.setdefault(neighbor, DualPerRootCounters())

    def clear_counters(self, neighbor: str) -> None:
        if neighbor in self.counters:
            self.counters[neighbor] = DualPerRootCounters()

    # -- SPT children --------------------------------------------------------

    def add_child(self, child: str) -> None:
        self._children.add(child)

    def remove_child(self, child: str) -> None:
        self._children.discard(child)

    def children(self) -> set[str]:
        return set(self._children)

    def spt_peers(self) -> set[str]:
        """nexthop + children when the route is valid (Dual.cpp:380-390)."""
        if not self.has_valid_route():
            return set()
        peers = self.children()
        peers.add(self.info.nexthop)
        return peers

    def has_valid_route(self) -> bool:
        return (
            self.info.sm.state == DualState.PASSIVE
            and self.info.distance != INFINITY64
            and self.info.nexthop is not None
        )

    # -- internals (Dual.cpp:84-293) ----------------------------------------

    def _neighbor_up(self, neighbor: str) -> bool:
        return self.local_distances.get(neighbor, INFINITY64) != INFINITY64

    def _min_distance(self) -> int:
        if self.node_id == self.root_id:
            return 0
        dmin = INFINITY64
        for nb, ld in self.local_distances.items():
            rd = self.info.neighbor_infos.setdefault(
                nb, NeighborInfo()
            ).report_distance
            dmin = min(dmin, add_distances(ld, rd))
        return dmin

    def _route_affected(self) -> bool:
        if not self.local_distances:
            return False
        if self.info.nexthop == self.node_id:
            return False  # I'm the root
        dmin = self._min_distance()
        if self.info.distance != dmin:
            return True
        if dmin == INFINITY64:
            return False
        nexthops = {
            nb
            for nb, ld in self.local_distances.items()
            if add_distances(
                ld, self.info.neighbor_infos[nb].report_distance
            )
            == dmin
        }
        assert self.info.nexthop is not None
        return self.info.nexthop not in nexthops

    def _meet_feasible_condition(self) -> Optional[tuple[str, int]]:
        """SNC: a neighbor with report-distance < my feasible distance on a
        min-distance path (Dual.cpp:148-169)."""
        dmin = self._min_distance()
        for nb, ld in self.local_distances.items():
            if ld == INFINITY64:
                continue
            rd = self.info.neighbor_infos.setdefault(
                nb, NeighborInfo()
            ).report_distance
            if rd < self.info.feasible_distance and add_distances(ld, rd) == dmin:
                return nb, dmin
        return None

    def _mk_msg(self, mtype: DualMessageType, distance: int) -> DualMessage:
        return DualMessage(dst_id=self.root_id, distance=distance, type=mtype)

    def _queue(self, out: MsgsToSend, neighbor: str, msg: DualMessage) -> None:
        out.setdefault(neighbor, DualMessages()).messages.append(msg)
        cnt = self._cnt(neighbor)
        if msg.type == DualMessageType.UPDATE:
            cnt.update_sent += 1
        elif msg.type == DualMessageType.QUERY:
            cnt.query_sent += 1
        else:
            cnt.reply_sent += 1
        cnt.total_sent += 1

    def _flood_updates(self, out: MsgsToSend) -> None:
        for nb, ld in self.local_distances.items():
            if ld == INFINITY64:
                continue
            self._queue(
                out,
                nb,
                self._mk_msg(DualMessageType.UPDATE, self.info.report_distance),
            )

    def _set_nexthop(self, new_nh: Optional[str]) -> None:
        if self.info.nexthop != new_nh:
            if self.nexthop_cb:
                self.nexthop_cb(self.info.nexthop, new_nh)
            self.info.nexthop = new_nh

    def _local_computation(
        self, new_nexthop: str, new_distance: int, out: MsgsToSend
    ) -> None:
        """Dual.cpp:191-211."""
        same_rd = new_distance == self.info.report_distance
        self._set_nexthop(new_nexthop)
        self.info.distance = new_distance
        self.info.report_distance = new_distance
        self.info.feasible_distance = new_distance
        if not same_rd:
            self._flood_updates(out)

    def _diffusing_computation(self, out: MsgsToSend) -> bool:
        """Dual.cpp:213-246."""
        ld = self.local_distances[self.info.nexthop]
        rd = self.info.neighbor_infos[self.info.nexthop].report_distance
        new_distance = add_distances(ld, rd)
        self.info.distance = new_distance
        self.info.report_distance = new_distance
        self.info.feasible_distance = new_distance

        success = False
        for nb, ld in self.local_distances.items():
            if ld == INFINITY64:
                continue
            self._queue(
                out,
                nb,
                self._mk_msg(DualMessageType.QUERY, self.info.report_distance),
            )
            self.info.neighbor_infos.setdefault(
                nb, NeighborInfo()
            ).expect_reply = True
            success = True
        return success

    def _send_reply(self, out: MsgsToSend) -> None:
        """Dual.cpp:566-594."""
        assert self.info.cornet, "send reply called on empty cornet"
        dst = self.info.cornet.pop()
        if not self._neighbor_up(dst):
            # link down on my end: reply when it comes up (Dual.cpp:574-584)
            self.info.neighbor_infos.setdefault(
                dst, NeighborInfo()
            ).need_to_reply = True
            return
        self._queue(
            out,
            dst,
            self._mk_msg(DualMessageType.REPLY, self.info.report_distance),
        )

    def _try_local_or_diffusing(
        self, event: DualEvent, need_reply: bool, out: MsgsToSend
    ) -> None:
        """Dual.cpp:248-293."""
        if not self._route_affected():
            if need_reply:
                self._send_reply(out)
            return
        fc = self._meet_feasible_condition()
        if self.info.nexthop is None:
            assert fc is not None, "nexthop invalid, must meet FC"
        if fc is not None:
            self._local_computation(fc[0], fc[1], out)
            if need_reply:
                self._send_reply(out)
        else:
            if need_reply and event != DualEvent.QUERY_FROM_SUCCESSOR:
                self._send_reply(out)
            success = self._diffusing_computation(out)
            if success:
                self.info.sm.process_event(event, False)
            if self.info.nexthop is not None and not self._neighbor_up(
                self.info.nexthop
            ):
                self._set_nexthop(None)

    # -- events (Dual.cpp:401-527) ------------------------------------------

    def peer_up(self, neighbor: str, cost: int, out: MsgsToSend) -> None:
        if self.info.nexthop == neighbor:
            # chose this neighbor before a non-graceful restart: reset
            # as-if peer-down had been seen (Dual.cpp:409-418)
            self._set_nexthop(None)
            self.info.distance = INFINITY64
        self.local_distances[neighbor] = cost
        self.info.neighbor_infos.setdefault(neighbor, NeighborInfo())

        if self.info.sm.state == DualState.PASSIVE:
            self._try_local_or_diffusing(DualEvent.OTHERS, False, out)
        else:
            if self.info.neighbor_infos[neighbor].expect_reply:
                # expected reply arrived via link-up (Dual.cpp:429-438)
                self.process_reply(
                    neighbor,
                    self._mk_msg(
                        DualMessageType.REPLY,
                        self.info.neighbor_infos[neighbor].report_distance,
                    ),
                    out,
                )

        # send my current report distance (Dual.cpp:441-451)
        self._queue(
            out,
            neighbor,
            self._mk_msg(DualMessageType.UPDATE, self.info.report_distance),
        )
        if self.info.neighbor_infos[neighbor].need_to_reply:
            self.info.neighbor_infos[neighbor].need_to_reply = False
            self._queue(
                out,
                neighbor,
                self._mk_msg(DualMessageType.REPLY, self.info.report_distance),
            )

    def peer_down(self, neighbor: str, out: MsgsToSend) -> None:
        self.clear_counters(neighbor)
        self.remove_child(neighbor)
        self.local_distances[neighbor] = INFINITY64
        self.info.neighbor_infos.setdefault(
            neighbor, NeighborInfo()
        ).report_distance = INFINITY64
        if self.info.sm.state == DualState.PASSIVE:
            self._try_local_or_diffusing(DualEvent.INCREASE_D, False, out)
        else:
            self.info.sm.process_event(DualEvent.INCREASE_D)
            if self.info.neighbor_infos[neighbor].expect_reply:
                # equivalent to a max-distance reply (Dual.cpp:490-499)
                self.process_reply(
                    neighbor,
                    self._mk_msg(DualMessageType.REPLY, INFINITY64),
                    out,
                )

    def peer_cost_change(self, neighbor: str, cost: int, out: MsgsToSend) -> None:
        event = (
            DualEvent.INCREASE_D
            if cost > self.local_distances.get(neighbor, INFINITY64)
            else DualEvent.OTHERS
        )
        self.local_distances[neighbor] = cost
        if self.info.sm.state == DualState.PASSIVE:
            self._try_local_or_diffusing(event, False, out)
        else:
            if self.info.nexthop == neighbor:
                self.info.distance = add_distances(
                    cost, self.info.neighbor_infos[neighbor].report_distance
                )
            self.info.sm.process_event(event)

    # -- messages (Dual.cpp:529-715) ----------------------------------------

    def process_update(
        self, neighbor: str, update: DualMessage, out: MsgsToSend
    ) -> None:
        assert update.type == DualMessageType.UPDATE
        assert update.dst_id == self.root_id
        cnt = self._cnt(neighbor)
        cnt.update_recv += 1
        cnt.total_recv += 1
        self.info.neighbor_infos.setdefault(
            neighbor, NeighborInfo()
        ).report_distance = update.distance
        if neighbor not in self.local_distances:
            return  # UPDATE before LINK-UP (Dual.cpp:548-551)
        if self.info.sm.state == DualState.PASSIVE:
            self._try_local_or_diffusing(DualEvent.OTHERS, False, out)
        else:
            if self.info.nexthop == neighbor:
                self.info.distance = add_distances(
                    self.local_distances[neighbor], update.distance
                )
            self.info.sm.process_event(DualEvent.OTHERS)

    def process_query(
        self, neighbor: str, query: DualMessage, out: MsgsToSend
    ) -> None:
        assert query.type == DualMessageType.QUERY
        assert query.dst_id == self.root_id
        cnt = self._cnt(neighbor)
        cnt.query_recv += 1
        cnt.total_recv += 1
        self.info.neighbor_infos.setdefault(
            neighbor, NeighborInfo()
        ).report_distance = query.distance
        self.info.cornet.append(neighbor)
        event = (
            DualEvent.QUERY_FROM_SUCCESSOR
            if self.info.nexthop == neighbor
            else DualEvent.OTHERS
        )
        if self.info.sm.state == DualState.PASSIVE:
            self._try_local_or_diffusing(event, True, out)
        else:
            if self.info.nexthop == neighbor:
                self.info.distance = add_distances(
                    self.local_distances[neighbor],
                    self.info.neighbor_infos[neighbor].report_distance,
                )
            self.info.sm.process_event(event)
            self._send_reply(out)

    def process_reply(
        self, neighbor: str, reply: DualMessage, out: MsgsToSend
    ) -> None:
        assert reply.type == DualMessageType.REPLY
        assert reply.dst_id == self.root_id
        cnt = self._cnt(neighbor)
        cnt.reply_recv += 1
        cnt.total_recv += 1
        ninfo = self.info.neighbor_infos.setdefault(neighbor, NeighborInfo())
        if not ninfo.expect_reply:
            # link-down raced the reply; ignore (Dual.cpp:651-658)
            return
        ninfo.report_distance = reply.distance
        ninfo.expect_reply = False
        if any(i.expect_reply for i in self.info.neighbor_infos.values()):
            return

        # last reply: free to pick the optimal route (Dual.cpp:676-706)
        self.info.sm.process_event(DualEvent.LAST_REPLY, True)
        dmin = INFINITY64
        new_nh: Optional[str] = None
        for nb, ld in self.local_distances.items():
            d = add_distances(
                ld, self.info.neighbor_infos[nb].report_distance
            )
            if d < dmin:
                dmin = d
                new_nh = nb
        same_rd = dmin == self.info.report_distance
        self.info.distance = dmin
        self.info.report_distance = dmin
        self.info.feasible_distance = dmin
        self._set_nexthop(new_nh)
        if not same_rd:
            self._flood_updates(out)

        if self.info.cornet:
            assert len(self.info.cornet) == 1, (
                "one diffusing per destination"
            )
            self._send_reply(out)

    # -- introspection -------------------------------------------------------

    def status_string(self) -> str:
        return f"root({self.root_id})::{self.node_id}: {self.info}"


class DualNode:
    """Multi-root DUAL driver (reference: class DualNode, Dual.h:315-412).

    Subclass or compose: provide `send_dual_messages(neighbor, msgs)` and
    `process_nexthop_change(root_id, old_nh, new_nh)` callbacks."""

    def __init__(
        self,
        node_id: str,
        is_root: bool = False,
        send_dual_messages: Optional[
            Callable[[str, DualMessages], bool]
        ] = None,
        process_nexthop_change: Optional[
            Callable[[str, Optional[str], Optional[str]], None]
        ] = None,
    ) -> None:
        self.node_id = node_id
        self.is_root = is_root
        self._send = send_dual_messages
        self._nexthop_change = process_nexthop_change
        self.local_distances: dict[str, int] = {}
        self.duals: dict[str, Dual] = {}
        self.pkt_counters: dict[str, dict[str, int]] = {}
        if is_root:
            self._add_dual(node_id)

    # -- hooks ---------------------------------------------------------------

    def send_dual_messages(self, neighbor: str, msgs: DualMessages) -> bool:
        if self._send is None:
            return False
        return self._send(neighbor, msgs)

    def process_nexthop_change(
        self, root_id: str, old_nh: Optional[str], new_nh: Optional[str]
    ) -> None:
        if self._nexthop_change is not None:
            self._nexthop_change(root_id, old_nh, new_nh)

    # -- events --------------------------------------------------------------

    def peer_up(self, neighbor: str, cost: int) -> None:
        self.local_distances[neighbor] = cost
        out: MsgsToSend = {}
        for dual in self.duals.values():
            dual.peer_up(neighbor, cost, out)
        self._send_all(out)

    def peer_down(self, neighbor: str) -> None:
        self.local_distances[neighbor] = INFINITY64
        self.pkt_counters.pop(neighbor, None)
        out: MsgsToSend = {}
        for dual in self.duals.values():
            dual.peer_down(neighbor, out)
        self._send_all(out)

    def peer_cost_change(self, neighbor: str, cost: int) -> None:
        self.local_distances[neighbor] = cost
        out: MsgsToSend = {}
        for dual in self.duals.values():
            dual.peer_cost_change(neighbor, cost, out)
        self._send_all(out)

    def process_dual_messages(self, messages: DualMessages) -> None:
        out: MsgsToSend = {}
        neighbor = messages.src_id
        cnt = self.pkt_counters.setdefault(
            neighbor, {"pkt_recv": 0, "msg_recv": 0, "pkt_sent": 0, "msg_sent": 0}
        )
        cnt["pkt_recv"] += 1
        cnt["msg_recv"] += len(messages.messages)
        for msg in messages.messages:
            root_id = msg.dst_id
            self._add_dual(root_id)
            dual = self.duals[root_id]
            if msg.type == DualMessageType.UPDATE:
                dual.process_update(neighbor, msg, out)
            elif msg.type == DualMessageType.QUERY:
                dual.process_query(neighbor, msg, out)
            elif msg.type == DualMessageType.REPLY:
                dual.process_reply(neighbor, msg, out)
        self._send_all(out)

    # -- getters -------------------------------------------------------------

    def has_dual(self, root_id: str) -> bool:
        return root_id in self.duals

    def get_dual(self, root_id: str) -> Dual:
        return self.duals[root_id]

    def get_spt_root_id(self) -> Optional[str]:
        """Smallest root-id with a valid route (Dual.cpp:788-803)."""
        for root_id in sorted(self.duals):
            if self.duals[root_id].has_valid_route():
                return root_id
        return None

    def get_spt_peers(self, root_id: Optional[str]) -> set[str]:
        if root_id is None or root_id not in self.duals:
            return set()
        return self.duals[root_id].spt_peers()

    def get_info(self, root_id: str) -> Optional[RouteInfo]:
        dual = self.duals.get(root_id)
        return dual.info if dual else None

    def get_infos(self) -> dict[str, RouteInfo]:
        return {r: d.info for r, d in self.duals.items()}

    def neighbor_up(self, neighbor: str) -> bool:
        return self.local_distances.get(neighbor, INFINITY64) != INFINITY64

    def status_strings(self) -> dict[str, str]:
        return {r: d.status_string() for r, d in self.duals.items()}

    # -- internal ------------------------------------------------------------

    def _send_all(self, out: MsgsToSend) -> None:
        for neighbor, msgs in out.items():
            if not msgs.messages:
                continue
            msgs.src_id = self.node_id
            if not self.send_dual_messages(neighbor, msgs):
                log.error("failed to send dual messages to %s", neighbor)
                continue
            cnt = self.pkt_counters.setdefault(
                neighbor,
                {"pkt_recv": 0, "msg_recv": 0, "pkt_sent": 0, "msg_sent": 0},
            )
            cnt["pkt_sent"] += 1
            cnt["msg_sent"] += len(msgs.messages)

    def _add_dual(self, root_id: str) -> None:
        if root_id in self.duals:
            return

        def cb(old_nh: Optional[str], new_nh: Optional[str], root=root_id):
            self.process_nexthop_change(root, old_nh, new_nh)

        self.duals[root_id] = Dual(
            self.node_id, root_id, self.local_distances, cb
        )
