"""Standalone FibService platform agent.

The reference ships `platform_linux` (LinuxPlatformMain.cpp), a separate
process whose NetlinkFibHandler (openr/platform/NetlinkFibHandler.h)
implements the thrift FibService (openr/if/Platform.thrift:71-160) and
programs the Linux kernel via netlink.  The TPU-native equivalent keeps
the same process boundary and API surface with two backends:

- SimulatedRouteTable (default): in-process table for clusterless tests
  (the MockNetlinkFibHandler pattern).
- KernelRouteTable (`--kernel`): programs REAL kernel routes through the
  from-scratch rtnetlink codec (openr_tpu.nl.netlink RTM_NEWROUTE /
  DELROUTE incl. RTA_MULTIPATH), with the reference's client->protocol
  mapping (Platform.thrift:58 clientIdtoProtocolId) and read-back via
  protocol-filtered route dumps (getRouteTableByClient,
  openr/platform/NetlinkFibHandler.h).  Requires CAP_NET_ADMIN.

The daemon's Fib module talks to it over the NDJSON-RPC wire transport,
and `breeze fib validate` audits daemon state against the agent's table.

Run standalone:  python -m openr_tpu.platform.fib_agent --port 60100 [--kernel]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import socket
import threading
import time
from typing import Any, Iterable, Optional

from ..serializer import from_wire, to_wire
from ..types import MplsAction, MplsActionCode, MplsRoute, NextHop, UnicastRoute

log = logging.getLogger(__name__)


class SimulatedRouteTable:
    """The agent-side route store (reference: NetlinkFibHandler's kernel
    programming + per-client route tracking; simulated kernel).

    Thread-safe: the server may run handlers from multiple connections."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # millisecond resolution: a sub-second supervisor restart must
        # still change aliveSince or Fib's keepalive never resyncs
        # (MockFibAgent.restart makes the same guarantee)
        self._alive_since = int(time.time() * 1000)
        self.unicast: dict[int, dict[str, UnicastRoute]] = {}
        self.mpls: dict[int, dict[int, MplsRoute]] = {}
        self.counters: dict[str, int] = {}

    def _bump(self, counter: str, n: int = 1) -> None:
        self.counters[counter] = self.counters.get(counter, 0) + n

    # -- FibService API (Platform.thrift:71-160) -----------------------------

    def add_unicast_routes(
        self, client_id: int, routes: list[UnicastRoute]
    ) -> None:
        with self._lock:
            table = self.unicast.setdefault(client_id, {})
            for route in routes:
                table[route.dest] = route
            self._bump("fibagent.add_unicast", len(routes))

    def delete_unicast_routes(
        self, client_id: int, prefixes: list[str]
    ) -> None:
        with self._lock:
            table = self.unicast.setdefault(client_id, {})
            for prefix in prefixes:
                table.pop(prefix, None)
            self._bump("fibagent.del_unicast", len(prefixes))

    def add_mpls_routes(self, client_id: int, routes: list[MplsRoute]) -> None:
        with self._lock:
            table = self.mpls.setdefault(client_id, {})
            for route in routes:
                table[route.top_label] = route
            self._bump("fibagent.add_mpls", len(routes))

    def delete_mpls_routes(self, client_id: int, labels: list[int]) -> None:
        with self._lock:
            table = self.mpls.setdefault(client_id, {})
            for label in labels:
                table.pop(label, None)
            self._bump("fibagent.del_mpls", len(labels))

    def sync_fib(self, client_id: int, routes: list[UnicastRoute]) -> None:
        with self._lock:
            self.unicast[client_id] = {r.dest: r for r in routes}
            self._bump("fibagent.sync_fib")

    def sync_mpls_fib(self, client_id: int, routes: list[MplsRoute]) -> None:
        with self._lock:
            self.mpls[client_id] = {r.top_label: r for r in routes}
            self._bump("fibagent.sync_mpls_fib")

    def get_route_table_by_client(self, client_id: int) -> list[UnicastRoute]:
        with self._lock:
            return sorted(
                self.unicast.get(client_id, {}).values(),
                key=lambda r: r.dest,
            )

    def get_mpls_route_table_by_client(self, client_id: int) -> list[MplsRoute]:
        with self._lock:
            return sorted(
                self.mpls.get(client_id, {}).values(),
                key=lambda r: r.top_label,
            )

    def alive_since(self) -> int:
        return self._alive_since

    def get_counters(self) -> dict[str, int]:
        with self._lock:
            return dict(self.counters)


# reference: Platform.thrift:58 — kernel route protocol per FibService
# client (rtnetlink rtm_protocol must be < 254)
CLIENT_ID_TO_PROTOCOL = {786: 99, 0: 253}
DEFAULT_PROTOCOL = 99


class KernelRouteTable:
    """FibService backend programming REAL kernel routes
    (reference: NetlinkFibHandler, openr/platform/NetlinkFibHandler.h).

    Unicast v4/v6 incl. multipath ride RTM_NEWROUTE/DELROUTE through the
    nl codec; per-client separation uses the kernel protocol id exactly
    like the reference (clientIdtoProtocolId).  MPLS label routes are
    programmed as AF_MPLS kernel routes (RTA_VIA + RTA_NEWDST) and READ
    BACK from the kernel — get_mpls_route_table_by_client and
    sync_mpls_fib diff against kernel truth, so they survive an agent
    restart (reference: getMplsRouteTableByClient,
    openr/platform/NetlinkFibHandler.cpp).  On kernels without AF_MPLS
    support (mpls_router not loaded) the first programming attempt trips
    a fallback to in-process tracking, logged once.
    """

    def __init__(self, table_id: Optional[int] = None) -> None:
        from ..nl.netlink import NetlinkProtocolSocket, RT_TABLE_MAIN

        self._lock = threading.Lock()
        self._alive_since = int(time.time() * 1000)
        self.nl = NetlinkProtocolSocket()
        self.table_id = RT_TABLE_MAIN if table_id is None else table_id
        # in-process MPLS mirror: authoritative ONLY when the kernel
        # lacks AF_MPLS (self._mpls_kernel is False)
        self.mpls: dict[int, dict[int, MplsRoute]] = {}
        self._mpls_kernel: Optional[bool] = None  # None = not yet probed
        self.counters: dict[str, int] = {}
        self._if_index: dict[str, int] = {}

    def _bump(self, counter: str, n: int = 1) -> None:
        self.counters[counter] = self.counters.get(counter, 0) + n

    @staticmethod
    def _protocol(client_id: int) -> int:
        proto = CLIENT_ID_TO_PROTOCOL.get(client_id)
        if proto is None:
            # the reference rejects unknown clients (getProtocol ->
            # ENOTSUPPORTED); silently aliasing them onto openr's
            # protocol would let a stray client wipe openr's FIB
            raise ValueError(f"unknown FibService client id {client_id}")
        return proto

    def _ifindex(self, if_name: Optional[str]) -> int:
        if not if_name:
            return 0
        idx = self._if_index.get(if_name)
        if idx is None:
            self._if_index = {
                l.if_name: l.if_index for l in self.nl.get_all_links()
            }
            # negative-cache misses: a vanished interface must not cost a
            # full link dump per route.  The entry self-heals: any
            # add_route failure drops negative entries so an interface
            # that appears later is picked up (_drop_negative_ifcache).
            idx = self._if_index.setdefault(if_name, 0)
        return idx

    def _drop_negative_ifcache(self, route: UnicastRoute) -> bool:
        """Invalidate negative (index 0) cache entries named by `route`'s
        nexthops; True when any was dropped (retry is worthwhile)."""
        dropped = False
        for nh in route.next_hops:
            if nh.if_name and self._if_index.get(nh.if_name) == 0:
                del self._if_index[nh.if_name]
                dropped = True
        return dropped

    def _add_route(self, client_id: int, route: UnicastRoute) -> None:
        """add_route with negative-ifindex self-healing: a failure whose
        route referenced a negatively-cached interface re-dumps the link
        map and retries once — a newly-appeared interface must not stay
        invisible until an unrelated cache miss (advisor r3).  Only
        errnos a missing OIF can cause trigger the retry (EINVAL: v6
        link-local gateway without device; ENODEV): unrelated failures
        must not pay a link dump + doomed resend per route."""
        import errno as _errno

        from ..nl.netlink import NetlinkError

        try:
            self.nl.add_route(self._to_route_info(client_id, route))
        except NetlinkError as exc:
            if exc.errno not in (_errno.EINVAL, _errno.ENODEV):
                raise
            if not self._drop_negative_ifcache(route):
                raise
            self.nl.add_route(self._to_route_info(client_id, route))

    def _to_route_info(self, client_id: int, route: UnicastRoute):
        from ..nl.netlink import NextHopInfo, RouteInfo

        nexthops = [
            NextHopInfo(
                gateway=nh.address or None,
                if_index=self._ifindex(nh.if_name),
                weight=max(nh.weight, 1),
                # SR label PUSH rides the MPLS lwtunnel encap
                push_labels=(
                    tuple(nh.mpls_action.push_labels)
                    if nh.mpls_action is not None
                    and nh.mpls_action.action == MplsActionCode.PUSH
                    and nh.mpls_action.push_labels
                    else ()
                ),
            )
            for nh in route.next_hops
        ]
        return RouteInfo(
            dst=route.dest,
            table=self.table_id,
            protocol=self._protocol(client_id),
            nexthops=nexthops,
        )

    # -- FibService API ------------------------------------------------------

    def add_unicast_routes(
        self, client_id: int, routes: list[UnicastRoute]
    ) -> None:
        with self._lock:
            for route in routes:
                self._add_route(client_id, route)
            self._bump("fibagent.kernel.add_unicast", len(routes))

    def delete_unicast_routes(
        self, client_id: int, prefixes: list[str]
    ) -> None:
        from ..nl.netlink import NetlinkError, RouteInfo

        with self._lock:
            for prefix in prefixes:
                try:
                    self.nl.del_route(
                        RouteInfo(
                            dst=prefix,
                            table=self.table_id,
                            protocol=self._protocol(client_id),
                        )
                    )
                except NetlinkError as exc:
                    import errno as _errno

                    if exc.errno != _errno.ESRCH:  # already gone: idempotent
                        raise
            self._bump("fibagent.kernel.del_unicast", len(prefixes))

    # -- MPLS: kernel AF_MPLS programming with readback -----------------------

    def _to_mpls_route_info(self, client_id: int, route: MplsRoute):
        from ..nl.netlink import MplsRouteInfo, NextHopInfo

        nexthops = []
        for nh in route.next_hops:
            act = nh.mpls_action
            swap: tuple = ()
            gateway = nh.address or None
            if act is not None:
                # `is not None`: swap to label 0 (explicit null) is legal
                # and must not degrade to a pop
                if (
                    act.action == MplsActionCode.SWAP
                    and act.swap_label is not None
                ):
                    swap = (act.swap_label,)
                elif act.action == MplsActionCode.PUSH and act.push_labels:
                    swap = tuple(act.push_labels)
                elif act.action == MplsActionCode.POP_AND_LOOKUP:
                    gateway = None  # oif-only: kernel pops + looks up
            nexthops.append(
                NextHopInfo(
                    gateway=gateway,
                    if_index=self._ifindex(nh.if_name),
                    weight=max(nh.weight, 1),
                    swap_labels=swap,
                )
            )
        return MplsRouteInfo(
            label=route.top_label,
            protocol=self._protocol(client_id),
            nexthops=nexthops,
        )

    def _mpls_try_kernel(self, op) -> bool:
        """Run an AF_MPLS netlink op; returns False (and latches the
        in-process fallback) when the kernel has no MPLS support."""
        import errno as _errno

        from ..nl.netlink import NetlinkError

        if self._mpls_kernel is False:
            return False
        try:
            op()
            self._mpls_kernel = True
            return True
        except NetlinkError as exc:
            if self._mpls_kernel is None and exc.errno in (
                _errno.EAFNOSUPPORT,
                getattr(_errno, "EPFNOSUPPORT", _errno.EAFNOSUPPORT),
                _errno.EPROTONOSUPPORT,
                _errno.EOPNOTSUPP,
            ):
                log.warning(
                    "kernel has no AF_MPLS support (%s); falling back to "
                    "in-process MPLS route tracking",
                    exc,
                )
                self._mpls_kernel = False
                return False
            raise

    def add_mpls_routes(self, client_id: int, routes: list[MplsRoute]) -> None:
        with self._lock:
            for route in routes:
                info = self._to_mpls_route_info(client_id, route)
                if not self._mpls_try_kernel(
                    lambda info=info: self.nl.add_mpls_route(info)
                ):
                    self.mpls.setdefault(client_id, {})[
                        route.top_label
                    ] = route
            self._bump("fibagent.kernel.add_mpls", len(routes))

    def delete_mpls_routes(self, client_id: int, labels: list[int]) -> None:
        import errno as _errno

        from ..nl.netlink import MplsRouteInfo, NetlinkError

        with self._lock:
            for label in labels:
                info = MplsRouteInfo(
                    label=label, protocol=self._protocol(client_id)
                )
                try:
                    programmed = self._mpls_try_kernel(
                        lambda info=info: self.nl.del_mpls_route(info)
                    )
                except NetlinkError as exc:
                    if exc.errno != _errno.ESRCH:  # already gone
                        raise
                    programmed = True
                if not programmed:
                    self.mpls.setdefault(client_id, {}).pop(label, None)
            self._bump("fibagent.kernel.del_mpls", len(labels))

    def sync_fib(self, client_id: int, routes: list[UnicastRoute]) -> None:
        """Full-state sync: program everything advertised, withdraw every
        kernel route of this client's protocol not in the set (reference:
        NetlinkFibHandler::future_syncFib keep/add/remove diff)."""
        import ipaddress

        with self._lock:
            # canonical prefix strings: the kernel readback is normalized
            # (e.g. "2001:0DB8::/64" comes back "2001:db8::/64"), so the
            # diff must compare canonical forms or syncs churn
            wanted = {
                str(ipaddress.ip_network(r.dest)): r for r in routes
            }
            current = {
                r.dst
                for r in self.nl.get_routes(
                    protocol=self._protocol(client_id), table=self.table_id
                )
            }
            # collect per-route failures and STILL run the stale-route
            # deletion pass: one bad add must not leave this client's
            # stale kernel routes behind until the next sync (advisor
            # r3; mirrors the reference's keep/add/remove diff)
            from ..nl.netlink import NetlinkError, RouteInfo

            errors: list[str] = []
            for route in routes:
                try:
                    self._add_route(client_id, route)
                except NetlinkError as exc:
                    errors.append(f"{route.dest}: {exc}")
            for dst in current - set(wanted):
                try:
                    self.nl.del_route(
                        RouteInfo(
                            dst=dst,
                            table=self.table_id,
                            protocol=self._protocol(client_id),
                        )
                    )
                except NetlinkError as exc:
                    errors.append(f"del {dst}: {exc}")
            self._bump("fibagent.kernel.sync_fib")
            if errors:
                raise RuntimeError(
                    f"sync_fib: {len(errors)} route(s) failed: "
                    + "; ".join(errors[:8])
                )

    def sync_mpls_fib(self, client_id: int, routes: list[MplsRoute]) -> None:
        """Full MPLS sync diffed against KERNEL readback (not in-process
        state), so a restarted agent still withdraws stale label routes —
        the round-3 gap this closes (reference: future_syncMplsFib,
        openr/platform/NetlinkFibHandler.cpp)."""
        from ..nl.netlink import MplsRouteInfo, NetlinkError

        with self._lock:
            wanted = {r.top_label for r in routes}
            proto = self._protocol(client_id)
            current: set[int] = set()
            dump_ok = False
            if self._mpls_kernel is not False:
                try:
                    current = {
                        r.label for r in self.nl.get_mpls_routes(proto)
                    }
                    # a successful dump does NOT prove AF_MPLS support
                    # (the kernel answers dumps for unregistered families
                    # with an empty set) — only a successful ADD latches
                    # _mpls_kernel=True, via _mpls_try_kernel below
                    dump_ok = True
                except OSError:
                    # transient dump failure (ENOBUFS, timeout) or
                    # no-MPLS kernel; the adds below decide which
                    pass
            errors: list[str] = []
            kernel_mode = True
            for route in routes:
                info = self._to_mpls_route_info(client_id, route)
                try:
                    if not self._mpls_try_kernel(
                        lambda info=info: self.nl.add_mpls_route(info)
                    ):
                        kernel_mode = False
                        break
                except NetlinkError as exc:
                    errors.append(f"label {route.top_label}: {exc}")
            if not kernel_mode or self._mpls_kernel is False:
                self.mpls[client_id] = {r.top_label: r for r in routes}
            elif dump_ok:
                for label in current - wanted:
                    try:
                        self.nl.del_mpls_route(
                            MplsRouteInfo(label=label, protocol=proto)
                        )
                    except NetlinkError as exc:
                        errors.append(f"del label {label}: {exc}")
            else:
                # stale-route withdrawal NEEDS the readback; skipping it
                # silently would leave stale labels while reporting
                # success — surface it so Fib's backoff retries the sync
                errors.append(
                    "kernel MPLS readback failed; stale-route deletion "
                    "skipped"
                )
            self._bump("fibagent.kernel.sync_mpls_fib")
            if errors:
                raise RuntimeError(
                    f"sync_mpls_fib: {len(errors)} route(s) failed: "
                    + "; ".join(errors[:8])
                )

    def get_route_table_by_client(self, client_id: int) -> list[UnicastRoute]:
        with self._lock:
            index_name = {
                l.if_index: l.if_name for l in self.nl.get_all_links()
            }
            out = []
            for r in self.nl.get_routes(
                protocol=self._protocol(client_id), table=self.table_id
            ):
                out.append(
                    UnicastRoute(
                        dest=r.dst,
                        next_hops=[
                            NextHop(
                                address=nh.gateway or "",
                                if_name=index_name.get(nh.if_index),
                                weight=nh.weight,
                            )
                            for nh in r.nexthops
                        ],
                    )
                )
            return sorted(out, key=lambda r: r.dest)

    def get_mpls_route_table_by_client(self, client_id: int) -> list[MplsRoute]:
        """Kernel readback of this client's AF_MPLS routes, with nexthop
        actions inferred from the wire form (RTA_NEWDST stack -> SWAP/
        PUSH, bare via -> PHP, oif-only -> POP_AND_LOOKUP); in-process
        table only on no-MPLS kernels.

        Wire-fidelity caveat: a single-label PUSH and a SWAP are the SAME
        kernel route (one-entry RTA_NEWDST), so readback reports SWAP for
        both; programmed weight 0 reads back as 1 (rtnh_hops).  Consumers
        must not full-equality-diff readback against intent —
        sync_mpls_fib correctly diffs by label only."""
        with self._lock:
            if self._mpls_kernel is False:
                return sorted(
                    self.mpls.get(client_id, {}).values(),
                    key=lambda r: r.top_label,
                )
            try:
                kernel_routes = self.nl.get_mpls_routes(
                    self._protocol(client_id)
                )
            except OSError:
                if self._mpls_kernel is True:
                    # kernel mode is established: a transient dump
                    # failure must surface, not read back as an empty
                    # table (the in-process dict is empty in this mode)
                    raise
                # unprobed kernel: may simply lack AF_MPLS
                return sorted(
                    self.mpls.get(client_id, {}).values(),
                    key=lambda r: r.top_label,
                )
            index_name = {
                l.if_index: l.if_name for l in self.nl.get_all_links()
            }
            out = []
            for r in kernel_routes:
                hops = []
                for nh in r.nexthops:
                    if nh.swap_labels and len(nh.swap_labels) == 1:
                        act = MplsAction(
                            MplsActionCode.SWAP,
                            swap_label=nh.swap_labels[0],
                        )
                    elif nh.swap_labels:
                        act = MplsAction(
                            MplsActionCode.PUSH,
                            push_labels=tuple(nh.swap_labels),
                        )
                    elif nh.gateway is not None:
                        act = MplsAction(MplsActionCode.PHP)
                    else:
                        act = MplsAction(MplsActionCode.POP_AND_LOOKUP)
                    hops.append(
                        NextHop(
                            address=nh.gateway or "",
                            if_name=index_name.get(nh.if_index),
                            weight=nh.weight,
                            mpls_action=act,
                        )
                    )
                out.append(MplsRoute(top_label=r.label, next_hops=hops))
            return sorted(out, key=lambda r: r.top_label)

    def alive_since(self) -> int:
        return self._alive_since

    def get_counters(self) -> dict[str, int]:
        with self._lock:
            return dict(self.counters)


class FibAgentServer:
    """NDJSON-RPC server fronting a SimulatedRouteTable — the process
    boundary the reference crosses with thrift (Fib -> platform agent)."""

    def __init__(
        self,
        table: Any = None,  # SimulatedRouteTable | KernelRouteTable
        host: str = "::1",
        port: int = 0,
    ) -> None:
        self.table = table or SimulatedRouteTable()
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()

    # method table: wire name (Platform.thrift) -> handler
    def _dispatch(self, method: str, p: dict) -> Any:
        t = self.table
        if method == "addUnicastRoutes":
            return t.add_unicast_routes(p["clientId"], p["routes"])
        if method == "deleteUnicastRoutes":
            return t.delete_unicast_routes(p["clientId"], p["prefixes"])
        if method == "addMplsRoutes":
            return t.add_mpls_routes(p["clientId"], p["routes"])
        if method == "deleteMplsRoutes":
            return t.delete_mpls_routes(p["clientId"], p["topLabels"])
        if method == "syncFib":
            return t.sync_fib(p["clientId"], p["routes"])
        if method == "syncMplsFib":
            return t.sync_mpls_fib(p["clientId"], p["routes"])
        if method == "getRouteTableByClient":
            return t.get_route_table_by_client(p["clientId"])
        if method == "getMplsRouteTableByClient":
            return t.get_mpls_route_table_by_client(p["clientId"])
        if method == "aliveSince":
            return t.alive_since()
        if method == "getCounters":
            return t.get_counters()
        raise ValueError(f"unknown method {method!r}")

    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            while True:
                line = await reader.readline()
                if not line:
                    return
                try:
                    msg = json.loads(line)
                except ValueError:
                    msg = None  # malformed line: error reply with no id
                msg_id = msg.get("id") if isinstance(msg, dict) else None
                try:
                    if not isinstance(msg, dict):
                        raise ValueError("malformed request")
                    result = self._dispatch(
                        msg.get("method", ""), from_wire(msg.get("params")) or {}
                    )
                    reply = {"id": msg_id, "result": to_wire(result)}
                except Exception as exc:  # surfaced to the client
                    reply = {
                        "id": msg_id,
                        "error": f"{type(exc).__name__}: {exc}",
                    }
                writer.write(json.dumps(reply).encode() + b"\n")
                await writer.drain()
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        finally:
            writer.close()

    async def _serve(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        self._started.set()
        async with self._server:
            await self._server.serve_forever()

    def start(self) -> None:
        """Serve in a background thread (for in-process tests); the
        standalone entry point uses run_forever() instead."""

        def _run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)
            try:
                self._loop.run_until_complete(self._serve())
            except asyncio.CancelledError:
                pass
            finally:
                self._loop.close()

        self._thread = threading.Thread(
            target=_run, name="fib-agent", daemon=True
        )
        self._thread.start()
        assert self._started.wait(10), "fib agent failed to start"

    def stop(self) -> None:
        if self._loop is not None:

            def _stop():
                for task in asyncio.all_tasks(self._loop):
                    task.cancel()

            self._loop.call_soon_threadsafe(_stop)
        if self._thread is not None:
            self._thread.join(5)

    def run_forever(self) -> None:
        asyncio.run(self._serve())


class TcpFibAgent:
    """Client side: implements the Fib module's FibAgent protocol over the
    agent's wire transport (reference: Fib::createFibClient, Fib.h:68).

    Synchronous (called from the Fib event-base thread); one persistent
    connection, reconnected on failure — a failed call raises, which drives
    Fib's retry/backoff + full-resync machinery exactly like a thrift
    transport error does in the reference."""

    def __init__(self, host: str = "::1", port: int = 60100, timeout_s: float = 5.0):
        self.host = host
        self.port = port
        self.timeout_s = timeout_s
        self._sock: Optional[socket.socket] = None
        self._file = None
        self._next_id = 0

    def _connect(self) -> None:
        if self._sock is not None:
            return
        info = socket.getaddrinfo(
            self.host, self.port, type=socket.SOCK_STREAM
        )[0]
        sock = socket.socket(info[0], info[1])
        sock.settimeout(self.timeout_s)
        sock.connect(info[4])
        self._sock = sock
        self._file = sock.makefile("rwb")

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
                self._file = None

    def _call(self, method: str, params: dict) -> Any:
        self._connect()
        self._next_id += 1
        request = {
            "id": self._next_id,
            "method": method,
            "params": to_wire(params),
        }
        try:
            self._file.write(json.dumps(request).encode() + b"\n")
            self._file.flush()
            line = self._file.readline()
        except OSError:
            self.close()
            raise
        if not line:
            self.close()
            raise ConnectionError("fib agent closed connection")
        msg = json.loads(line)
        if "error" in msg:
            raise RuntimeError(f"fib agent error: {msg['error']}")
        return from_wire(msg.get("result"))

    # -- FibAgent protocol ---------------------------------------------------

    def add_unicast_routes(
        self, client_id: int, routes: list[UnicastRoute]
    ) -> None:
        self._call("addUnicastRoutes", {"clientId": client_id, "routes": routes})

    def delete_unicast_routes(
        self, client_id: int, prefixes: list[str]
    ) -> None:
        self._call(
            "deleteUnicastRoutes", {"clientId": client_id, "prefixes": prefixes}
        )

    def add_mpls_routes(self, client_id: int, routes: list[MplsRoute]) -> None:
        self._call("addMplsRoutes", {"clientId": client_id, "routes": routes})

    def delete_mpls_routes(self, client_id: int, labels: list[int]) -> None:
        self._call(
            "deleteMplsRoutes", {"clientId": client_id, "topLabels": labels}
        )

    def sync_fib(self, client_id: int, routes: list[UnicastRoute]) -> None:
        self._call("syncFib", {"clientId": client_id, "routes": routes})

    def sync_mpls_fib(self, client_id: int, routes: list[MplsRoute]) -> None:
        self._call("syncMplsFib", {"clientId": client_id, "routes": routes})

    def get_route_table_by_client(self, client_id: int) -> list[UnicastRoute]:
        return self._call("getRouteTableByClient", {"clientId": client_id})

    def get_mpls_route_table_by_client(self, client_id: int) -> list[MplsRoute]:
        return self._call("getMplsRouteTableByClient", {"clientId": client_id})

    def alive_since(self) -> int:
        return int(self._call("aliveSince", {}))

    def get_counters(self) -> dict[str, int]:
        return self._call("getCounters", {})


def main(argv: Optional[Iterable[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="Standalone FibService platform agent "
        "(reference: platform_linux / LinuxPlatformMain.cpp)"
    )
    parser.add_argument("--host", default="::1")
    parser.add_argument("--port", type=int, default=60100)
    parser.add_argument(
        "--kernel",
        action="store_true",
        help="program REAL kernel routes via rtnetlink (needs "
        "CAP_NET_ADMIN); default is the simulated table",
    )
    parser.add_argument(
        "--route-table",
        type=int,
        default=None,
        help="kernel routing table id (default: main/254)",
    )
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(list(argv) if argv is not None else None)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    table = (
        KernelRouteTable(table_id=args.route_table) if args.kernel else None
    )
    server = FibAgentServer(table=table, host=args.host, port=args.port)
    print(f"fib-agent listening on [{args.host}]:{args.port}", flush=True)
    try:
        server.run_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
