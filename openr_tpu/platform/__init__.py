"""Platform layer: the standalone FibService agent a router's Fib module
programs routes into (reference: openr/platform/ — NetlinkFibHandler served
by the `platform_linux` binary, LinuxPlatformMain.cpp)."""

from .fib_agent import (  # noqa: F401
    FibAgentServer,
    SimulatedRouteTable,
    TcpFibAgent,
)
